"""Packed single-collective state sync — the epoch-boundary communication plan.

The eager sync path (``Metric._sync_dist``) issues one host collective PER state
tensor — and one per list-state element — each behind its own metadata gather.
At epoch end a 4-metric stat-scores collection therefore pays ≥ 8 collectives
for a few KB of state. This module replaces that with a bounded plan:

1. **One metadata exchange** (when needed at all): a single fixed-shape int32
   gather carrying, for every dynamic state, its leading-dim size / element
   count plus a shape fingerprint. Plans whose states are all fixed-shape
   (every shape equals its registered default's — the common
   sum/mean/max/min case) are *rank-invariant* and skip the exchange entirely.
2. **One all-gather per (role, dtype) buffer**: every sum/mean-reduced state
   packs into a flat ``reduce:{dtype}`` buffer (the gather-then-sum fold is the
   ``psum`` of the host world; on a mesh backbone the same buffer rides an
   actual ``psum``), and everything else — max/min, raw ``None``-stacked
   arrays, custom folds, ragged ``cat`` states and list-state elements — packs
   into a ``gather:{dtype}`` buffer, ragged segments padded to the world max
   known from the metadata.
3. **One fold graph**: unpacking + every state's ``dist_reduce_fx`` fold lower
   into a single jittable function (:meth:`PackedSyncPlan.make_fold`), cached
   by the caller per :meth:`PackedSyncPlan.signature`.

A plan can span several metrics (``MetricCollection`` compute-group owners), so
an entire collection syncs in O(dtypes) collectives regardless of how many
metrics and states are live.

Eligibility is explicit: anything the pack cannot express — host-object list
elements, list states with a non-``cat``/``None`` reduction, states that are
not arrays — raises :class:`PackingError` at plan build and the caller falls
back to the eager per-tensor path (counted, never silent). Cross-rank layout
violations that would deadlock the eager path (ragged list counts, mismatched
element shapes) are detected from the metadata exchange and fail loud on every
rank with the same errors the eager guard raises.
"""

from __future__ import annotations

import functools
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu.diag import profile as _profile
from torchmetrics_tpu.diag import sentinel as _sentinel
from torchmetrics_tpu.diag import timeline as _timeline

from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "PackedSyncPlan",
    "PackingError",
    "all_gather_backbone",
    "ingraph_sync_mode",
    "mesh_world_view",
]

# metadata entry tags (first int of nothing — entries are positional, tags are
# implicit in the spec order; kept here as documentation of the 2-int layout)
_META_INTS_PER_ENTRY = 2


def _is_array(x: Any) -> bool:
    import jax
    import jax.numpy as jnp

    return isinstance(x, (jax.Array, jnp.ndarray)) and not isinstance(x, (list, tuple))


# tmlint: host-only — digests python int sequences, never device buffers
def _fingerprint(dims: Sequence[int]) -> int:
    """Process-stable digest of a dim sequence (crc32, masked to positive int32)."""
    return zlib.crc32(np.asarray(list(dims), dtype=np.int64).tobytes()) & 0x7FFFFFFF


def _snapshot_cat_array(value: Any) -> Optional[Any]:
    """Normalize a snapshot cat-state value (list or array) to a packed array.

    Mirrors what the eager list path packs: elements concatenate along a
    leading axis (scalars promote to length-1 rows). Returns ``None`` for an
    empty list — the caller treats it as a zero-row contribution.
    """
    import jax.numpy as jnp

    if isinstance(value, (list, tuple)):
        if not value:
            return None
        return jnp.concatenate([jnp.atleast_1d(jnp.asarray(e)) for e in value], axis=0)
    if value is None:
        return None
    arr = jnp.asarray(value)
    return arr.reshape(1) if arr.ndim == 0 else arr


def all_gather_backbone(x: Any, label: str = "", members: Optional[Sequence[int]] = None) -> Any:
    """The host collective: one ``process_allgather`` returning ``(world, ...)``.

    Isolated here so tests and benches can monkeypatch a fake world, and so a
    future mesh backbone (``axis_gather``/``axis_sum`` inside ``shard_map``)
    can slot in without touching the plan logic.

    This is THE sanctioned host-transfer boundary of the packed sync: the body
    runs inside :func:`~torchmetrics_tpu.diag.transfer_allowed` (state must
    cross hosts here by definition, so a strict transfer guard over the epoch
    must not flag it) and each issue is recorded as a ``collective`` flight-
    recorder event carrying its role/dtype ``label`` (the plan's buffer key,
    e.g. ``"reduce:int32"``, or ``"meta"``) and payload bytes.

    The raw collective rides :func:`~torchmetrics_tpu.parallel.resilience.
    bounded_collective`: a configured deadline/retry policy bounds it (typed
    :class:`~torchmetrics_tpu.parallel.resilience.SyncFaultError` instead of an
    indefinite hang), and the fault-injection harness (``parallel/faults.py``)
    plants its faults here — ``members`` is the plan's live membership, which
    rank-scoped faults consult (a degraded re-plan's excluded rank no longer
    fires). With no policy and no faults active the wrapper is a direct call.
    """
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from torchmetrics_tpu.diag import trace as _diag
    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed
    from torchmetrics_tpu.parallel.resilience import bounded_collective

    _diag.record("collective", "", label=label, bytes=int(getattr(x, "nbytes", 0)))
    with transfer_allowed("collective:" + label):
        # the lambda re-reads process_allgather at call time so retries see the
        # live (possibly monkeypatched) collective
        return jnp.asarray(
            bounded_collective(
                lambda: multihost_utils.process_allgather(x, tiled=False),
                label=label,
                payload=x,
                members=members,
            )
        )


def ingraph_sync_mode(plan: "PackedSyncPlan", mesh: Any, data_size: int) -> Optional[str]:
    """Can this plan's buffer exchange ride the mesh's ``"data"`` axis?

    Returns ``"emulated"`` (one real process emulating ``world_size`` ranks —
    tests/bench worlds patched over ``jax.process_count``), ``"spmd"`` (a real
    multi-process world whose mesh gives each process exactly its own data
    row), or ``None`` (ride the host packed gather).

    The gate is strict by design — every condition below guards a correctness
    edge, and a counted host fallback always remains available:

    - the data axis must equal the plan's world size (each rank = one row, so
      the fold's ``stacked.<op>(axis=0)`` over the row-sharded dim IS the
      cross-rank fold);
    - degraded/sub-world plans stay on the host path (the fold's member
      sub-select indexes the world axis — exact on a host-gathered buffer,
      but a data-sharded view would still carry the excluded rank's row);
    - in a real multi-process world, row ``i`` of the mesh must hold process
      ``i``'s devices and nothing else — a process-local mesh there would
      tile LOCAL buffers over the data axis and silently double-count.
    """
    if mesh is None or plan.world_size < 2 or data_size != plan.world_size:
        return None
    if plan.degraded or plan.members != tuple(range(plan.world_size)):
        return None
    import jax

    try:
        real_procs = {d.process_index for d in jax.devices()}
    except Exception:  # noqa: BLE001 — un-initialized backend: host path
        return None
    if len(real_procs) == 1:
        return "emulated"
    rows = mesh.devices.reshape(plan.world_size, -1)
    for i in range(plan.world_size):
        if {d.process_index for d in rows[i].flat} != {i}:
            return None
    return "spmd"


def mesh_world_view(
    buf: Any, world_size: int, mesh: Any, multiprocess: bool = False, label: str = ""
) -> Any:
    """Device-resident ``(world, n)`` gathered view sharded over ``"data"``.

    The in-graph replacement for :func:`all_gather_backbone`: instead of a
    host ``process_allgather``, the world view of a packed buffer is
    assembled as a device array whose leading (world) dim is partitioned over
    the mesh's ``"data"`` axis. When the fold executable consumes it, GSPMD
    lowers ``stacked.sum(axis=0)`` to a local partial + in-graph ``psum``
    over ``"data"`` (``pmax``/``pmin``/``all_gather`` for the other kinds) —
    the cross-rank collective compiles into the same program as the unpack
    and fold, and zero bytes cross the host boundary.

    Emulated worlds (``multiprocess=False``: one real process standing in for
    ``world_size`` ranks): every rank's buffer IS this buffer, so the view is
    a broadcast stack resharded over ``"data"`` — value-identical to the host
    gather's stacked result, with the same per-row contents the patched
    ``process_allgather`` of the test worlds produces. Tests monkeypatch THIS
    function to emulate distinct per-rank buffers.

    Real multi-host (``multiprocess=True``): each process contributes its
    local buffer as its own data row via
    ``jax.make_array_from_single_device_arrays`` — no host collective; the
    exchange happens in-graph when the fold runs.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from torchmetrics_tpu.parallel import sharding as _sharding

    sh = NamedSharding(mesh, PartitionSpec(_sharding.DATA_AXIS))
    buf = jnp.asarray(buf)
    if not multiprocess:
        stacked = jnp.broadcast_to(buf[None], (world_size,) + tuple(buf.shape))
        return jax.device_put(stacked, sh)
    row = buf[None]
    # every addressable device of the sharding belongs to this process's data
    # row; each holds the full (1, n) row shard (replicated over "state")
    arrays = [jax.device_put(row, d) for d in sh.addressable_devices]
    return jax.make_array_from_single_device_arrays(
        (world_size,) + tuple(buf.shape), sh, arrays
    )


class PackingError(Exception):
    """This state layout cannot ride the packed plan — fall back to eager sync."""


class _Spec:
    """One state's slot in the packed buffers."""

    __slots__ = (
        "owner", "attr", "kind", "fold_fn", "dtype", "shape", "elem_shapes",
        "group", "offset", "size", "world_dim0", "pad_to", "needs_meta",
        "was_list", "packed_value", "hh_meta", "rank_invariant",
    )

    def __init__(self, owner: str, attr: str, kind: str, dtype: str, fold_fn: Optional[Callable] = None):
        self.owner = owner
        self.attr = attr
        self.kind = kind  # sum | mean | max | min | none-array | custom | cat | none-list
        self.fold_fn = fold_fn  # custom callable folds only
        self.hh_meta: Optional[Tuple] = None  # hh-ids only: (cms attr, k, depth, width)
        self.rank_invariant = False  # audit: values must match on every rank
        self.dtype = dtype
        self.shape: Tuple[int, ...] = ()
        self.elem_shapes: Tuple[Tuple[int, ...], ...] = ()  # none-list only
        self.group = ""
        self.offset = 0
        self.size = 0  # flat length of this spec's segment (incl. ragged padding)
        self.world_dim0: Tuple[int, ...] = ()  # cat only: per-MEMBER true dim0
        self.pad_to = 0  # cat only: FULL-WORLD max dim0 (every rank packs the collective)
        self.needs_meta = False
        self.was_list = False
        self.packed_value = None  # cat lists: concatenated once at build time


class PackedSyncPlan:
    """Sync plan over one or more metrics' registered states.

    Usage (the epoch engine drives this)::

        plan = PackedSyncPlan([(name, metric), ...], world_size, process_group)
        meta = plan.metadata_local()            # None when rank-invariant
        plan.finalize(world_meta)               # world_meta None when meta was
        local = plan.pack()                     # {buffer_key: flat device array}
        gathered = {k: backbone(v) for ...}     # ONE collective per buffer
        fold = jax.jit(plan.make_fold())        # cached by plan.signature()
        states = fold(gathered)                 # {owner: {attr: synced value}}
    """

    def __init__(
        self,
        metrics: Sequence[Tuple[str, Any]],
        world_size: int,
        process_group: Optional[Sequence[int]] = None,
    ) -> None:
        if world_size < 1:
            raise PackingError("world size < 1")
        self.world_size = int(world_size)
        self.members: Tuple[int, ...] = (
            tuple(range(self.world_size)) if process_group is None else tuple(int(i) for i in process_group)
        )
        self._metrics = list(metrics)
        self._finalized = False
        self._group_sizes: Dict[str, int] = {}
        self.specs: List[_Spec] = []
        self.empty_lists: List[Tuple[str, str]] = []  # cat/none lists empty on this rank
        # divergence audit (opt-in, diag/sentinel.py): per-state value
        # fingerprints piggyback on the metadata gather; enablement is frozen
        # at plan build and MUST match on every rank (layout symmetry — safe
        # to gate on world_size, which is identical everywhere). One-process
        # worlds skip it entirely: no cross-rank comparison can ever flag.
        self.audit = _sentinel.audit_enabled() and self.world_size > 1
        self.audit_results: List[Dict[str, Any]] = []
        self._audit_nonzero: List[bool] = []  # local-buffer any() per audited spec
        # cross-rank timeline (opt-in via profiling, diag/profile.py): barrier
        # pre/post timestamps piggyback on the metadata gather, layout-versioned.
        # Same symmetry rule as sentinel/audit: enablement is a function of the
        # knob alone and MUST match on every rank; a rank-invariant plan loses
        # its zero-metadata shortcut while profiling is on (one gather buys the
        # whole straggler/clock-offset story — a deliberate, documented cost).
        self.timeline = _profile.timeline_enabled() and self.world_size > 1
        self.timeline_result: Optional[Dict[str, Any]] = None
        # degraded-mode markers (engine/epoch.py sets them on a re-plan over
        # surviving membership): a partial fold is never a silent fact — the
        # marker rides the plan, the count rides EngineStats.sync_degraded_folds,
        # the event rides the flight recorder, the series rides Prometheus.
        # Membership-keyed invalidation is structural: `members` is part of
        # signature(), so a degraded fold can never be served by a full-world
        # cached executable (or vice versa).
        self.degraded = False
        self.excluded_ranks: Tuple[int, ...] = ()
        # live-sharded states the gather skips entirely (parallel/sharding.py):
        # (owner, attr, fold, spans_processes) tuples the sync driver counts
        # as gather_skipped / psum_syncs — their cross-device sync is the
        # in-graph collective the SPMD executable already lowered. The
        # spans_processes flag drives the multi-host honesty warning: a
        # process-LOCAL mesh in a multi-process world folded only local
        # contributions.
        self.skipped_sharded: List[Tuple[str, str, str, bool]] = []
        self._build()

    # ------------------------------------------------------------------ build

    def _build(self) -> None:
        import jax.numpy as jnp

        # function-level import: packing sits below the engine package in the
        # import graph (engine/epoch.py imports this module at top level), so a
        # module-level engine import here would be a cycle
        from torchmetrics_tpu.engine import numerics as _numerics
        from torchmetrics_tpu.engine import statespec as _statespec
        from torchmetrics_tpu.engine import txn as _txn
        from torchmetrics_tpu.parallel import sharding as _sharding

        for owner, metric in self._metrics:
            # every packed-sync role resolves from the metric's registered
            # StateSpecs (engine/statespec.py) — fold semantics, the
            # heavy-hitter grid/ids/counts joint roles, rank invariance for
            # the audit. Metrics without a registry entry resolve through the
            # deprecated attribute-convention fallback, counted once per
            # (metric, state) in EngineStats.spec_fallbacks.
            sspecs = _statespec.specs_of(metric, consumer="packed-sync")
            # compensated accumulation (engine/numerics.py): membership is a
            # function of the ENABLEMENT KNOB + the metric definition alone —
            # never of live values — so enablement must match on every rank or
            # the buffer layouts desynchronize (the sentinel's documented rule,
            # enforced by the plan signature / layout checks)
            comp_names = (
                _numerics.comp_state_names(metric) if _numerics.compensated_enabled() else ()
            )
            if comp_names:
                _numerics.ensure_residuals(metric)
            # heavy-hitter roles (serve/sketch.py): the metric DEFINITION
            # declares a (ids, counts) pair that must fold JOINTLY against the
            # merged count-min grid — a dedicated packed role, not a per-state
            # reduction. Membership is a function of the definition alone (the
            # specs always exist), so rank layouts cannot desynchronize.
            names = list(metric._reductions)
            hh_ids_attr = next((n for n, sp in sspecs.items() if sp.role == "hh-ids"), None)
            counts_attr = next((n for n, sp in sspecs.items() if sp.role == "hh-counts"), None)
            if hh_ids_attr is not None:
                hh = sspecs[hh_ids_attr].hh
                grid_attr = hh[0] if hh else None
                if (
                    hh is None
                    or grid_attr not in names
                    or counts_attr is None
                    or names.index(grid_attr) > names.index(hh_ids_attr)
                    or names.index(counts_attr) != names.index(hh_ids_attr) + 1
                ):
                    raise PackingError(
                        "heavy-hitter fold requires the count-min grid registered before"
                        " the adjacent (ids, counts) top-k pair"
                    )
            elif counts_attr is not None:
                # an orphan hh-counts spec would be SKIPPED by the fold (it is
                # written with its paired ids) — silently keeping its local
                # per-rank value would desynchronize ranks; fail loud instead
                raise PackingError(
                    f"state {counts_attr!r} declares role 'hh-counts' with no paired"
                    " 'hh-ids' state — the heavy-hitter pair folds jointly"
                )
            elif getattr(metric, "_hh_fold_info", None) is not None:
                # a declared joint fold whose top-k pair never registered:
                # packing it as independent per-state folds would silently
                # break the exact-merge contract — fail loud like the old path
                raise PackingError(
                    "heavy-hitter fold requires the count-min grid registered before"
                    " the adjacent (ids, counts) top-k pair"
                )
            rank_inv_live = getattr(metric, "_rank_invariant_states", ()) or ()
            for attr, red in metric._reductions.items():
                val = getattr(metric, attr)
                default = metric._defaults[attr]
                sspec = sspecs[attr]
                if _is_array(val) and _sharding.is_sharded(val):
                    # a partitioned state is global by construction — the SPMD
                    # executable folded every device's contribution through
                    # in-graph psum/psum_scatter; packing it would gather
                    # buffers this host may not even address. Placement truth
                    # is a pure function of (metric definition, mesh policy),
                    # identical on every rank, so the layout-symmetry rule the
                    # buffer collectives depend on is preserved.
                    self.skipped_sharded.append(
                        (owner, attr, sspec.fold, _sharding.spans_processes(val))
                    )
                    continue
                if sspec.role in ("hh-ids", "hh-counts"):
                    if not _is_array(val):
                        raise PackingError(f"heavy-hitter state {attr!r} is not an array")
                    spec = _Spec(owner, attr, sspec.role, str(val.dtype))
                    spec.shape = tuple(int(d) for d in val.shape)
                    spec.size = int(np.prod(spec.shape, dtype=np.int64)) if spec.shape else 1
                    spec.needs_meta = tuple(getattr(default, "shape", ())) != spec.shape
                    spec.group = "gather:" + spec.dtype
                    if sspec.role == "hh-ids":
                        spec.hh_meta = tuple(sspec.hh)
                    self.specs.append(spec)
                    continue
                if isinstance(default, list):
                    if sspec.fold in ("cat", "none"):
                        self._add_list_spec(owner, metric, attr, red, val)
                    else:
                        raise PackingError(f"list state {attr!r} with non-cat reduction")
                    continue
                if not _is_array(val):
                    raise PackingError(f"state {attr!r} is not an array")
                fold_fn = None
                if sspec.fold in ("sum", "mean", "max", "min", "cat"):
                    kind = sspec.fold
                elif sspec.fold == "none":
                    kind = "none-array"
                elif sspec.fold == "custom":
                    kind, fold_fn = "custom", sspec.fold_fn or red
                else:
                    raise PackingError(f"unsupported reduction for state {attr!r}")
                spec = _Spec(owner, attr, kind, str(val.dtype), fold_fn)
                # instance-level declarations made after add_state still join
                # the audit: union of the registered spec and the live attr
                spec.rank_invariant = sspec.rank_invariant or attr in rank_inv_live
                spec.shape = tuple(int(d) for d in val.shape)
                spec.size = int(np.prod(spec.shape, dtype=np.int64)) if spec.shape else 1
                if kind == "cat":
                    # dim 0 may differ per rank; trailing dims must agree
                    if not spec.shape:
                        spec.shape = (1,)
                        spec.size = 1
                    spec.needs_meta = True
                else:
                    # non-cat folds need equal shapes on every rank (the eager
                    # path's jnp.stack has the same requirement); a state that
                    # has drifted from its registered default's shape gets a
                    # verification entry in the metadata exchange
                    spec.needs_meta = tuple(getattr(default, "shape", ())) != spec.shape
                spec.group = ("reduce:" if kind in ("sum", "mean") else "gather:") + spec.dtype
                if attr in comp_names and kind in ("sum", "mean"):
                    # paired (value, residual) fold via two-sum — not naive
                    # add: the value spec becomes comp-{sum,mean} and a
                    # residual spec rides the SAME reduce buffer right after
                    spec.kind = "comp-" + kind
                    self.specs.append(spec)
                    res_spec = _Spec(owner, attr, "comp-res", spec.dtype)
                    res_spec.shape = spec.shape
                    res_spec.size = spec.size
                    res_spec.group = spec.group
                    self.specs.append(res_spec)
                    continue
                self.specs.append(spec)
            # health sentinel (diag/sentinel.py): the int32 bitmask rides the
            # gather buffer and folds cross-rank by bitwise OR, so a flag
            # raised on ANY rank survives the sync. Membership is a function of
            # the ENABLEMENT KNOB alone — never of whether this particular
            # metric happens to carry a residual flags attribute — so ranks
            # with different sentinel history cannot desynchronize the buffer
            # layout as long as the knob matches world-wide (the documented
            # rule); a missing bitmask is created here (zero, one-time).
            sentinel_val = _sentinel.ensure_flags(metric) if _sentinel.sentinel_enabled() else None
            if _is_array(sentinel_val):
                spec = _Spec(owner, _sentinel.ATTR, "sentinel", str(sentinel_val.dtype))
                spec.shape = tuple(int(d) for d in sentinel_val.shape)
                spec.size = 1
                spec.group = "gather:" + spec.dtype
                self.specs.append(spec)
            # quarantine counter (engine/txn.py): the per-rank batch-quarantine
            # count rides the reduce buffer and SUMS across ranks — the same
            # additive fold the aggregate ``_update_count`` gets at checkpoint
            # restore. Membership is a function of the enablement knob alone
            # (the sentinel's layout-symmetry rule): enable the same mode on
            # every rank or the buffer layouts desynchronize.
            quarantine_val = _txn.ensure_count(metric) if _txn.quarantine_enabled() else None
            if _is_array(quarantine_val):
                spec = _Spec(owner, _txn.ATTR, "sum", str(quarantine_val.dtype))
                spec.shape = tuple(int(d) for d in quarantine_val.shape)
                spec.size = 1
                spec.needs_meta = False
                spec.group = "reduce:" + spec.dtype
                self.specs.append(spec)

    def _add_list_spec(self, owner: str, metric: Any, attr: str, red: Any, val: Any) -> None:
        import jax.numpy as jnp

        elements = val if isinstance(val, list) else [val]
        if not all(_is_array(x) for x in elements):
            raise PackingError(f"list state {attr!r} holds host objects")
        if red is dim_zero_cat:
            if not elements:
                self.empty_lists.append((owner, attr))
                # still participates in the metadata exchange via a zero-row
                # entry so mixed emptiness across ranks fails loud
                spec = _Spec(owner, attr, "cat", "", None)
                spec.shape = (0,)
                spec.size = 0
                spec.needs_meta = True
                spec.was_list = True
                self.specs.append(spec)
                return
            cat = dim_zero_cat(elements)
            spec = _Spec(owner, attr, "cat", str(cat.dtype), None)
            spec.shape = tuple(int(d) for d in cat.shape)
            spec.size = int(np.prod(spec.shape, dtype=np.int64))
            spec.needs_meta = True
            spec.was_list = True
            spec.packed_value = cat  # concatenated ONCE; pack() reuses it
            spec.group = "gather:" + spec.dtype
            self.specs.append(spec)
            return
        # None-reduced list: positional per-element semantics, equal counts and
        # per-position shapes required on every rank (the eager guard's rule)
        spec = _Spec(owner, attr, "none-list", str(elements[0].dtype) if elements else "", None)
        spec.elem_shapes = tuple(tuple(int(d) for d in e.shape) for e in elements)
        if elements and any(str(e.dtype) != spec.dtype for e in elements):
            raise PackingError(f"list state {attr!r} mixes element dtypes")
        spec.size = int(sum(np.prod(s, dtype=np.int64) if s else 1 for s in spec.elem_shapes))
        spec.needs_meta = True
        spec.was_list = True
        if elements:
            spec.group = "gather:" + spec.dtype
        self.specs.append(spec)

    # ------------------------------------------------------------------ metadata

    @property
    def rank_invariant(self) -> bool:
        """True when every shape is provably identical on all ranks — the
        metadata exchange is skipped entirely (zero extra collectives)."""
        return not any(s.needs_meta for s in self.specs)

    #: spec kinds the divergence audit fingerprints (fixed-shape array states;
    #: cat/list states are ragged by design and the sentinel is already ORed)
    _AUDITABLE = ("sum", "mean", "max", "min", "none-array", "custom")

    def _audit_specs(self) -> List[_Spec]:
        return [s for s in self.specs if s.kind in self._AUDITABLE]

    def metadata_local(self) -> Optional[np.ndarray]:
        """Fixed-shape int32 probe covering every dynamic state, or None.

        With the divergence audit on, every fixed-shape array state appends a
        ``(value fingerprint, element count)`` pair: a crc32 of the state's
        full float64-cast buffer — dtype-stable, so the x64 warmup's
        int32→int64 promotion does not read as divergence, while
        sum-preserving divergence (permuted rows, NaN-vs-zero) still changes
        the digest. Reading the values is a host transfer by design and rides
        the same sanctioned boundary as the gather itself.

        With profiling on (``diag/profile.py``), a layout-versioned timestamp
        triple (``diag/timeline.py``) is appended LAST — the cross-rank
        clock-offset / straggler story costs zero extra collectives, but a
        rank-invariant plan does lose its skip-the-gather shortcut.
        """
        entries: List[int] = []
        for s in self.specs:
            if not s.needs_meta:
                continue
            if s.kind == "cat":
                dim0 = s.shape[0] if s.size else 0
                entries += [dim0, _fingerprint(s.shape[1:]) if s.size else 0]
            elif s.kind == "none-list":
                dims: List[int] = []
                for es in s.elem_shapes:
                    dims.append(len(es))
                    dims.extend(es)
                entries += [len(s.elem_shapes), _fingerprint(dims)]
            else:  # static-shape verification entry
                entries += [s.size, _fingerprint(s.shape)]
        if self.audit:
            from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

            by_owner = dict(self._metrics)
            self._audit_nonzero = []
            with transfer_allowed("sync-audit"):
                for s in self._audit_specs():
                    value = np.asarray(getattr(by_owner[s.owner], s.attr))
                    if np.iscomplexobj(value):
                        value = np.abs(value)  # magnitude keeps the digest dtype-stable
                    # digest the FULL float64-cast buffer, not a sum: summing
                    # would miss sum-preserving divergence (permuted rows,
                    # NaN-vs-zero), which is exactly what the audit must catch
                    value = np.ascontiguousarray(value.astype(np.float64))
                    self._audit_nonzero.append(bool(value.any()))
                    entries += [
                        zlib.crc32(value.tobytes()) & 0x7FFFFFFF,
                        int(value.size) & 0x7FFFFFFF,
                    ]
        if self.timeline:
            # [layout version, previous barrier exit, current barrier arrival]
            # — appended LAST so the straggler tooling (and emulated-world test
            # helpers) can address the stamps without replaying the spec walk
            entries += _timeline.timeline_entries()
        if not entries:
            return None
        # tmlint: disable=TM101 — `entries` is a host list of python ints (the
        # audit digests above already rode the sanctioned sync-audit boundary)
        return np.asarray(entries, dtype=np.int32)

    def metadata_from_state(self, states: Dict[str, Dict[str, Any]]) -> Optional[np.ndarray]:
        """:meth:`metadata_local` computed from a SNAPSHOT state-dict.

        The federation aggregator (``serve/federation.py``) folds pod
        *snapshots* — ``{owner: {attr: value}}`` dicts that arrived through the
        verified ingest envelope — not live metrics, so the per-"rank" probe
        entries (cat dim0s, list layouts, static-shape fingerprints) must come
        from the provided arrays. Entry layout is identical to
        :meth:`metadata_local` with the audit/timeline riders off (the
        aggregation tier disables both on its plan: there is no cross-rank
        barrier to timestamp and the divergence audit's rank-invariance
        contract does not apply to independent pods).
        """
        entries: List[int] = []
        for s in self.specs:
            if not s.needs_meta:
                continue
            value = states.get(s.owner, {}).get(s.attr)
            if s.kind == "cat":
                arr = _snapshot_cat_array(value)
                if arr is None or arr.size == 0:
                    entries += [0, 0]
                else:
                    entries += [int(arr.shape[0]), _fingerprint(tuple(arr.shape[1:]))]
            elif s.kind == "none-list":
                elems = value if isinstance(value, (list, tuple)) else []
                dims: List[int] = []
                for e in elems:
                    es = tuple(np.shape(e))
                    dims.append(len(es))
                    dims.extend(es)
                entries += [len(elems), _fingerprint(dims)]
            else:  # static-shape verification entry
                shape = tuple(np.shape(value))
                size = int(np.prod(shape, dtype=np.int64)) if shape else 1
                entries += [size, _fingerprint(shape)]
        if not entries:
            return None
        # tmlint: disable=TM101 — `entries` is a host list of python ints
        # derived from snapshot shapes (no device buffer is read)
        return np.asarray(entries, dtype=np.int32)

    # tmlint: host-only — validates the GATHERED metadata (host numpy, arrived
    # through the sanctioned sync-metadata exchange); touches no device buffer
    def finalize(self, world_meta: Optional[np.ndarray]) -> None:
        """Validate the exchanged metadata and freeze buffer offsets.

        ``world_meta`` is the gathered ``(world, n_entries)`` probe (None when
        :meth:`metadata_local` returned None). Raises
        :class:`~torchmetrics_tpu.utilities.exceptions.TorchMetricsUserError`
        for layouts that would deadlock/corrupt the eager path — symmetric on
        every rank, since every rank sees the same world metadata.
        """
        if world_meta is not None:
            world_meta = np.asarray(world_meta)
            idx = 0
            for s in self.specs:
                if not s.needs_meta:
                    continue
                # layout validation runs over the FULL world: every rank —
                # sub-world member or not — enters the same buffer collectives,
                # so a layout mismatch anywhere wedges everyone
                counts = world_meta[:, idx]
                prints = world_meta[:, idx + 1]
                idx += _META_INTS_PER_ENTRY
                if s.kind == "cat":
                    nonzero = prints[counts > 0]
                    if nonzero.size and (nonzero.max() != nonzero.min()):
                        raise TorchMetricsUserError(
                            f"Cannot sync state `{s.attr}`: processes hold mismatched"
                            f" trailing shapes for the cat-reduced state (shape"
                            f" fingerprints {prints.tolist()})."
                        )
                    if not s.group and counts.max() > 0:
                        # empty cat LIST: the element dtype (hence the buffer
                        # layout) is unknowable on this rank while others hold rows
                        raise TorchMetricsUserError(
                            f"Cannot sync list state `{s.attr}`: processes hold differing"
                            f" element counts {counts.tolist()} — ranks with fewer elements"
                            " would skip collectives the rest enter and deadlock the"
                            " world. Ensure every process sees the same number of"
                            " updates before compute(), or skip syncing"
                            " (sync_on_compute=False) for ragged epochs."
                        )
                    s.world_dim0 = tuple(int(counts[i]) for i in self.members)
                    s.pad_to = int(counts.max())  # non-members pack the collective too
                elif s.kind == "none-list":
                    if counts.max() != counts.min():
                        raise TorchMetricsUserError(
                            f"Cannot sync list state `{s.attr}`: processes hold differing"
                            f" element counts {counts.tolist()} — ranks with fewer elements"
                            " would skip collectives the rest enter and deadlock the"
                            " world. Ensure every process sees the same number of"
                            " updates before compute(), or skip syncing"
                            " (sync_on_compute=False) for ragged epochs."
                        )
                    if counts.max() > 0 and prints.max() != prints.min():
                        raise TorchMetricsUserError(
                            f"Cannot sync list state `{s.attr}`: processes hold equal"
                            f" element counts but mismatched per-element shapes"
                            f" (shape fingerprints {prints.tolist()}). Positional"
                            " collectives over a None-reduced list state require"
                            " identical per-position shapes on every rank."
                        )
                else:  # static verification
                    if counts.max() != counts.min() or prints.max() != prints.min():
                        raise TorchMetricsUserError(
                            f"Cannot sync state `{s.attr}`: processes hold mismatched"
                            f" shapes (sizes {counts.tolist()}, fingerprints"
                            f" {prints.tolist()}); non-cat reductions require identical"
                            " state shapes on every rank."
                        )
            if self.audit:
                # divergence audit: compare every fixed-shape state's value
                # fingerprint across ranks BEFORE the fold destroys the
                # per-rank view. Divergence is normal for accumulating states
                # (each rank saw different batches); it is flagged only for
                # states the metric declares rank-invariant. Identical
                # sum/mean fingerprints are the opposite smell — every rank
                # appears to have accumulated the same stream, so the fold
                # will double-count — reported as "duplicate-suspect".
                self.audit_results = []
                for spec_i, s in enumerate(self._audit_specs()):
                    fps = world_meta[:, idx]
                    sizes = world_meta[:, idx + 1]
                    idx += _META_INTS_PER_ENTRY
                    divergent = bool(fps.max() != fps.min() or sizes.max() != sizes.min())
                    # identical fingerprints imply every rank's buffer equals
                    # the local one, so the LOCAL any() check is world-valid:
                    # all-zero (still-at-default) states are not suspicious
                    local_nonzero = spec_i < len(self._audit_nonzero) and self._audit_nonzero[spec_i]
                    if divergent and s.rank_invariant:
                        flag = "rank-invariant-divergence"
                    elif (
                        not divergent
                        and local_nonzero
                        and s.kind in ("sum", "mean")
                        and np.issubdtype(np.dtype(s.dtype), np.floating)
                    ):
                        # float accumulations over DIFFERENT data are never
                        # bitwise identical — identical float sums mean every
                        # rank saw the same stream and the fold double-counts.
                        # Integer count states are exempt: balanced sharding
                        # legitimately produces equal counts on every rank.
                        flag = "duplicate-suspect"
                    else:
                        flag = ""
                    self.audit_results.append(
                        {"owner": s.owner, "attr": s.attr, "kind": s.kind, "divergent": divergent, "flag": flag}
                    )
            if self.timeline:
                versions = world_meta[:, idx]
                prev_post = world_meta[:, idx + 1]
                arrivals = world_meta[:, idx + 2]
                idx += _timeline.TIMELINE_META_INTS
                if int(versions.max()) != int(versions.min()) or int(versions.max()) != _timeline.LAYOUT_VERSION:
                    # asymmetric profiling enablement (or a future layout bump)
                    # would mis-parse every later entry — fail loud on all ranks
                    raise TorchMetricsUserError(
                        f"Cannot sync: processes disagree on the packed-sync timeline"
                        f" layout (versions {versions.tolist()}, expected"
                        f" {_timeline.LAYOUT_VERSION}). Profiling"
                        " (TORCHMETRICS_TPU_PROFILE / profile_context) extends the"
                        " metadata layout and must be enabled on every rank or none."
                    )
                self.timeline_result = _timeline.resolve_arrivals(
                    prev_post, arrivals, self._local_rank()
                )
        # pad ragged cat segments to the FULL-WORLD max and freeze offsets
        offsets: Dict[str, int] = {}
        for s in self.specs:
            if s.kind == "cat" and s.pad_to:
                trailing = int(np.prod(s.shape[1:], dtype=np.int64)) if len(s.shape) > 1 else 1
                s.size = s.pad_to * trailing
            if not s.group:
                continue
            s.offset = offsets.get(s.group, 0)
            offsets[s.group] = s.offset + s.size
        self._group_sizes = dict(offsets)
        self._finalized = True

    @staticmethod
    def _local_rank() -> int:
        import jax

        try:
            return int(jax.process_index())
        except Exception:  # noqa: BLE001 — un-initialized backend reads as rank 0
            return 0

    # ------------------------------------------------------------------ pack

    def buffer_keys(self) -> List[str]:
        return sorted(self._group_sizes)

    def pack(self) -> Dict[str, Any]:
        """Concatenate every local state into its flat per-(role, dtype) buffer."""
        import jax.numpy as jnp

        if not self._finalized:
            raise RuntimeError("finalize() must run before pack()")
        from torchmetrics_tpu.engine import numerics as _numerics

        segments: Dict[str, List[Any]] = {k: [] for k in self._group_sizes}
        by_owner = dict(self._metrics)
        for s in self.specs:
            if not s.group or s.size == 0:
                continue
            if s.kind == "comp-res":
                val = _numerics.ensure_residuals(by_owner[s.owner])[s.attr]
            else:
                val = getattr(by_owner[s.owner], s.attr)
            if s.kind == "none-list":
                flat = jnp.concatenate([jnp.ravel(e) for e in val]) if val else jnp.zeros((0,))
            elif s.kind == "cat":
                arr = s.packed_value if s.was_list else jnp.asarray(val)
                if arr.ndim == 0:
                    arr = arr.reshape(1)
                flat = jnp.ravel(arr)
                if flat.size < s.size:  # ragged: pad to the world max
                    flat = jnp.pad(flat, (0, s.size - flat.size))
            else:
                flat = jnp.ravel(jnp.asarray(val))
            segments[s.group].append(flat)
        return {k: jnp.concatenate(v) for k, v in segments.items() if v}

    def pack_from(
        self,
        states: Dict[str, Dict[str, Any]],
        residuals: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> Dict[str, Any]:
        """:meth:`pack` over a SNAPSHOT state-dict instead of the live metrics.

        Each pod's verified snapshot packs into the exact per-(role, dtype)
        buffers the fold graph consumes, so one compiled
        :meth:`make_fold` executable serves the aggregation tier unchanged.
        ``residuals`` supplies the compensated-sum residual arrays per
        ``{owner: {attr: residual}}`` (a pod snapshot carries them in its
        envelope); an absent residual packs as zeros — the pod's value is then
        folded as a clean anchor, which is exactly what a non-compensated pod
        contributed.
        """
        import jax.numpy as jnp

        if not self._finalized:
            raise RuntimeError("finalize() must run before pack_from()")
        segments: Dict[str, List[Any]] = {k: [] for k in self._group_sizes}
        residuals = residuals or {}
        for s in self.specs:
            if not s.group or s.size == 0:
                continue
            if s.kind == "comp-res":
                val = residuals.get(s.owner, {}).get(s.attr)
                if val is None:
                    val = jnp.zeros(s.shape, dtype=s.dtype)
            else:
                val = states.get(s.owner, {}).get(s.attr)
            if s.kind == "none-list":
                elems = val if isinstance(val, (list, tuple)) else []
                flat = (
                    jnp.concatenate([jnp.ravel(jnp.asarray(e)) for e in elems])
                    if elems
                    else jnp.zeros((0,))
                )
            elif s.kind == "cat":
                arr = _snapshot_cat_array(val)
                flat = jnp.zeros((0,), dtype=s.dtype) if arr is None else jnp.ravel(arr)
                if flat.size < s.size:  # ragged: pad to the world max
                    flat = jnp.pad(flat, (0, s.size - flat.size))
                flat = flat.astype(s.dtype)
            else:
                flat = jnp.ravel(jnp.asarray(val))
            segments[s.group].append(flat)
        return {k: jnp.concatenate(v) for k, v in segments.items() if v}

    # ------------------------------------------------------------------ fold

    def coverage(self) -> Dict[str, Any]:
        """Membership attestation for values folded through this plan.

        The shape the provenance plane (``diag/lineage.py``) stamps on
        observations: who contributed, who was excluded by a degraded
        re-plan, and whether the fold covered the full world. Pure read of
        plan markers — no device access.
        """
        return {
            "members": [str(r) for r in self.members],
            "world_size": self.world_size,
            "degraded": self.degraded,
            "excluded": [{"id": str(r), "reason": "sync-fault"} for r in self.excluded_ranks],
            "complete": not self.degraded and len(self.members) == self.world_size,
        }

    def signature(self) -> Tuple:
        """Cache key for the fold executable: full static layout + world geometry."""
        return (
            self.world_size,
            self.members,
            tuple(sorted(self._group_sizes.items())),
            tuple(
                (
                    s.owner, s.attr, s.kind, s.dtype, s.shape, s.elem_shapes,
                    s.group, s.offset, s.size, s.world_dim0, s.was_list, s.fold_fn,
                    s.hh_meta,
                )
                for s in self.specs
            ),
            tuple(self.empty_lists),
        )

    def make_fold(self) -> Callable[[Dict[str, Any]], Dict[str, Dict[str, Any]]]:
        """Pure ``gathered buffers -> {owner: {attr: synced value}}`` fold.

        Jittable: every slice boundary is a static Python int from the plan, so
        the unpack + every state's ``dist_reduce_fx`` fold lower into one XLA
        graph. The caller jits and caches it per :meth:`signature`.
        """
        if not self._finalized:
            raise RuntimeError("finalize() must run before make_fold()")
        from torchmetrics_tpu.engine import numerics as _numerics

        specs = list(self.specs)
        members = list(self.members)
        empty = list(self.empty_lists)
        # comp-{sum,mean} specs pair with the comp-res spec appended right
        # after them at build time; resolve the pairing by position once
        res_pair: Dict[int, _Spec] = {
            i: specs[i + 1]
            for i, s in enumerate(specs)
            if s.kind in ("comp-sum", "comp-mean")
        }
        # hh-ids specs pair with the hh-counts spec registered right after
        # them (layout enforced at build time, like the comp-res pairing)
        hh_pair: Dict[int, _Spec] = {
            i: specs[i + 1] for i, s in enumerate(specs) if s.kind == "hh-ids"
        }

        def fold(gathered: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
            import jax.numpy as jnp

            out: Dict[str, Dict[str, Any]] = {}
            for spec_i, s in enumerate(specs):
                dest = out.setdefault(s.owner, {})
                if s.kind in ("comp-res", "hh-counts"):
                    continue  # folded with their paired value / hh-ids spec
                if s.kind == "cat" and (not s.group or (s.world_dim0 and max(s.world_dim0) == 0)):
                    # empty on every rank: lists stay [], arrays keep a 0-row shape
                    dest[s.attr] = (
                        [] if s.was_list or not s.group
                        else jnp.zeros((0,) + s.shape[1:], dtype=s.dtype)
                    )
                    continue
                if s.kind == "none-list" and not s.elem_shapes:
                    dest[s.attr] = []
                    continue
                seg = gathered[s.group][:, s.offset : s.offset + s.size]
                seg = seg[jnp.asarray(members)] if members != list(range(self.world_size)) else seg
                if s.kind in ("comp-sum", "comp-mean"):
                    # (value, residual) pairs fold via two-sum — not naive add:
                    # each rank's residual feeds back into its increment, the
                    # exact fold error carries forward, and the synced pair is
                    # re-anchored in the SAME graph (the epoch-boundary fold)
                    rs = res_pair[spec_i]
                    seg_r = gathered[rs.group][:, rs.offset : rs.offset + rs.size]
                    seg_r = (
                        seg_r[jnp.asarray(members)]
                        if members != list(range(self.world_size))
                        else seg_r
                    )
                    vstack = seg.reshape((len(members),) + s.shape)
                    rstack = seg_r.reshape((len(members),) + s.shape)
                    total, res = vstack[0], rstack[0]
                    for r in range(1, len(members)):
                        total, res = _numerics.two_sum(total, vstack[r] + rstack[r] + res)
                    total, res = _numerics.two_sum(total, res)  # clean anchor
                    if s.kind == "comp-mean":
                        total = total / len(members)
                        res = res / len(members)
                    dest[s.attr] = total
                    dest[_numerics.SYNC_RES_PREFIX + s.attr] = res
                elif s.kind == "hh-ids":
                    # joint heavy-hitter fold (serve/sketch.py): the union of
                    # every rank's top-k candidates, re-estimated against the
                    # MERGED count-min grid — which this same fold already
                    # summed (the grid's spec precedes the pair by contract),
                    # so the merge is exactly a single-rank pass over the
                    # union stream whenever each heavy id made some local list
                    from torchmetrics_tpu.serve.sketch import merge_topk

                    cms_attr, hh_k, hh_depth, hh_width = s.hh_meta
                    stacked = seg.reshape((len(members),) + s.shape)
                    ids, counts = merge_topk(
                        dest[cms_attr], stacked.reshape((-1,)), hh_k, hh_depth, hh_width
                    )
                    dest[s.attr] = ids.astype(s.dtype)
                    cs = hh_pair[spec_i]
                    dest[cs.attr] = counts.astype(cs.dtype)
                elif s.kind == "sentinel":
                    # per-bit max == bitwise OR: a health flag raised on ANY
                    # rank survives the cross-rank fold
                    stacked = seg.reshape((len(members),))
                    dest[s.attr] = functools.reduce(
                        jnp.bitwise_or, [stacked[r] for r in range(len(members))]
                    ).reshape(s.shape)
                elif s.kind in ("sum", "mean", "max", "min", "none-array", "custom"):
                    stacked = seg.reshape((len(members),) + s.shape)
                    if s.kind == "sum":
                        dest[s.attr] = stacked.sum(axis=0)
                    elif s.kind == "mean":
                        dest[s.attr] = stacked.mean(axis=0)
                    elif s.kind == "max":
                        dest[s.attr] = stacked.max(axis=0)
                    elif s.kind == "min":
                        dest[s.attr] = stacked.min(axis=0)
                    elif s.kind == "none-array":
                        dest[s.attr] = stacked
                    else:
                        dest[s.attr] = s.fold_fn(stacked)
                elif s.kind == "cat":
                    trailing = s.shape[1:]
                    tsize = int(np.prod(trailing, dtype=np.int64)) if trailing else 1
                    dims = s.world_dim0 or (s.shape[0],) * len(members)
                    parts = [
                        seg[r, : dims[r] * tsize].reshape((dims[r],) + trailing)
                        for r in range(len(members))
                        if dims[r]
                    ]
                    dest[s.attr] = jnp.concatenate(parts, axis=0)
                else:  # none-list: element-major interleave, eager-path order
                    elems: List[Any] = []
                    off = 0
                    for es in s.elem_shapes:
                        esize = int(np.prod(es, dtype=np.int64)) if es else 1
                        for r in range(len(members)):
                            elems.append(seg[r, off : off + esize].reshape(es))
                        off += esize
                    dest[s.attr] = elems
            for owner, attr in empty:
                out.setdefault(owner, {}).setdefault(attr, [])
            return out

        return fold

    def none_folded_attrs(self, owner: str) -> List[str]:
        """Attrs whose synced value carries a new leading shard axis."""
        return [s.attr for s in self.specs if s.owner == owner and s.kind == "none-array"]
