"""SPMD sharded-state engine — the mesh/pjit layer that makes ``shard_rule`` real.

Every state used to be replicated per rank: one whole copy per device, synced
at epoch end by the packed host gather. That caps state size at one device's
HBM — a million-class confusion matrix or vocab-level per-class counters are
simply unrepresentable. This module turns the ``StateSpec.shard_rule`` slot
(PR 11's landing pad, ``engine/statespec.py``) into actual placement:

- **Mesh manager.** :func:`metric_mesh` resolves the process-wide
  ``jax.sharding.Mesh`` the shard rules partition over — a 1-D mesh with the
  named axis :data:`STATE_AXIS` (``"state"``), built over the local devices
  (CPU multi-device via ``--xla_force_host_platform_device_count`` for
  tests/bench, real chips in production). Activation is explicit:
  :func:`mesh_context` / :func:`set_mesh` scoped overrides, or the
  ``TORCHMETRICS_TPU_SHARD`` env var (``"1"``/``"all"`` = every local device,
  an integer N = the first N; invalid values FAIL LOUD per the PR-7 env
  contract). With no active mesh every rule resolves to ``None`` and nothing
  changes — replicated state, today's semantics.

- **Born distributed.** ``Metric.add_state`` resolves the registered spec's
  rule through :func:`~torchmetrics_tpu.engine.statespec.resolve_shard_rule`
  and ``device_put``s the default onto the resolved ``NamedSharding`` — the
  state (and its registered default, so ``reset()`` keeps the placement) never
  materializes unsharded. A rule that cannot partition the value (no active
  mesh; a leading dim the mesh axis does not divide) degrades to replication,
  recorded as a ``shard.fallback`` event when a mesh was active.

- **SPMD executables.** The compiled-step engines (``engine/compiled.py``,
  ``engine/scan.py``, ``engine/fusion.py``) pass
  :func:`state_out_shardings` as ``jax.jit(..., out_shardings=...)`` and key
  their caches on :func:`placement_token`, so the donated update/scan
  executables lower as SPMD programs: the batch contribution is computed and
  scattered shard-locally, GSPMD inserts the in-graph ``psum`` /
  ``psum_scatter`` collectives the partitioning needs, and a re-placed state
  compiles a fresh signature instead of colliding with the replicated one.

- **Sync is in-graph.** A live-sharded state is *global by construction* —
  the SPMD program already folded every device's contribution through XLA
  collectives — so the packed host gather (``parallel/packing.py``) skips it
  entirely (``gather_skipped``; additive folds counted as ``psum_syncs``).
  Gathering it through the host would both defeat the point and, on a mesh
  spanning processes, read buffers this host cannot address.

- **Lifecycle.** Riders (``__sentinel__``/``__quarantine__`` scalars stay
  replicated; the ``__compensation__`` residual inherits its value's sharding
  via ``zeros_like``), scan carries, quarantine rollback selects, snapshot
  copies and clones all preserve placement because JAX propagates shardings
  through eager ops and ``deepcopy``. The paths that genuinely round-trip
  through host numpy — ``state_dict``/``load_state_dict``, pickling,
  ``restore_resharded`` — re-apply the registered rules via
  :func:`reshard_states` on restore.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Dict, Generator, Optional, Sequence, Tuple

from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.engine.stats import EngineStats
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "SHARD_ENV_VAR",
    "STATE_AXIS",
    "axis_size",
    "build_mesh",
    "is_sharded",
    "mesh_context",
    "metric_mesh",
    "partition_dim0",
    "place_state",
    "placement_token",
    "reshard_states",
    "set_mesh",
    "sharding_enabled",
    "state_out_shardings",
]

SHARD_ENV_VAR = "TORCHMETRICS_TPU_SHARD"

#: the named mesh axis shard rules partition over — ``"class_axis"`` /
#: ``"row_sharded"`` split a state's leading dim across it
STATE_AXIS = "state"

_UNSET = object()
_mesh_override: Any = _UNSET

# module-level stats block: mesh placement is a process-wide fact, not a
# per-engine property — one EngineStats joins the weak registry so
# engine_report()/telemetry aggregate it (the module global keeps it alive)
_STATS = EngineStats("sharding")

# set the first time any state is actually placed distributed; the per-step
# placement-token walk short-circuits to the pre-sharding O(1) token until
# then, so processes that never shard pay one bool check per dispatch
_ever_placed = False


# ------------------------------------------------------------------ mesh policy


def build_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence[Any]] = None):
    """A 1-D :class:`jax.sharding.Mesh` with the named axis ``"state"``.

    ``devices`` wins when given; otherwise the first ``n_devices`` of the
    GLOBAL device set (all of them when ``None``) — identical to the local
    set in a single process, and the only placement whose in-graph
    collectives actually span the world in a multi-process one (a
    process-local mesh there folds only local contributions; the sync driver
    warns when it sees that). Fewer than 2 devices is a loud error — a
    1-device "mesh" would silently demote every rule to replication while
    the operator believes sharding is on.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        world = jax.devices()
        if n_devices is not None:
            if not isinstance(n_devices, int) or isinstance(n_devices, bool) or n_devices < 2:
                raise TorchMetricsUserError(
                    f"a state mesh needs an integer device count >= 2 (got {n_devices!r})"
                )
            if n_devices > len(world):
                raise TorchMetricsUserError(
                    f"requested a {n_devices}-device state mesh but only"
                    f" {len(world)} devices exist (CPU tests: raise"
                    " --xla_force_host_platform_device_count)"
                )
            world = world[:n_devices]
        devices = world
    if len(devices) < 2:
        raise TorchMetricsUserError(
            f"a state mesh needs >= 2 devices (got {len(devices)}); with one"
            " device every shard rule is a no-op — leave sharding off instead"
        )
    # tmlint: disable=TM101 — `devices` is a host list of Device objects
    return Mesh(np.asarray(devices), (STATE_AXIS,))


def _env_mesh():
    """The mesh the ``TORCHMETRICS_TPU_SHARD`` env var names, or ``None``.

    ``""``/``"0"``/``"off"`` = off; ``"1"``/``"on"``/``"all"`` = every local
    device; an integer N >= 2 = the first N. Anything else fails loud (the
    PR-7 env contract: a typo must not silently change placement semantics).
    """
    raw = os.environ.get(SHARD_ENV_VAR, "").strip().lower()
    if raw in ("", "0", "off"):
        return None
    if raw in ("1", "on", "all"):
        return build_mesh()
    try:
        n = int(raw)
    except ValueError:
        raise TorchMetricsUserError(
            f"{SHARD_ENV_VAR}={raw!r} is not a valid state-mesh size (expected"
            " unset/'0'/'off', '1'/'on'/'all', or an integer N >= 2)"
        ) from None
    return build_mesh(n)


def metric_mesh():
    """The active state mesh, or ``None`` (sharding off — replicated state)."""
    if _mesh_override is not _UNSET:
        return _mesh_override
    return _env_mesh()


def set_mesh(mesh: Any = None) -> None:
    """Force the state mesh process-wide.

    Accepts a ready :class:`jax.sharding.Mesh`, an integer device count,
    ``True`` (all local devices), or ``False`` (force sharding OFF regardless
    of the env var — the same spelling :func:`mesh_context` accepts); ``None``
    restores env-var resolution.
    """
    global _mesh_override
    if mesh is None:
        _mesh_override = _UNSET
    elif mesh is False:
        # bool before int: isinstance(False, int) is True, and the build_mesh
        # size check would raise a baffling "got False" instead of disabling
        _mesh_override = None
    elif mesh is True:
        _mesh_override = build_mesh()
    elif isinstance(mesh, int):
        _mesh_override = build_mesh(mesh)
    else:
        _mesh_override = mesh


@contextmanager
def mesh_context(mesh: Any = True) -> Generator[Any, None, None]:
    """Scoped state-mesh activation (tests, benches, serving loops).

    ``mesh`` as in :func:`set_mesh` (``False`` forces sharding OFF inside the
    scope regardless of the env var). Yields the active mesh (or ``None``).
    Placement happens at ``add_state`` / :func:`reshard_states` time — states
    born inside the scope stay sharded after it exits (arrays are committed);
    only NEW placements see the restored policy.
    """
    global _mesh_override
    prev = _mesh_override
    set_mesh(mesh)
    try:
        yield metric_mesh()
    finally:
        _mesh_override = prev


def sharding_enabled() -> bool:
    """Whether an active mesh makes shard rules resolve to real placements."""
    return metric_mesh() is not None


def axis_size() -> int:
    """Devices along the ``"state"`` axis of the active mesh (1 when off)."""
    mesh = metric_mesh()
    return 1 if mesh is None else int(mesh.shape[STATE_AXIS])


# ------------------------------------------------------------------ predicates


def is_sharded(value: Any) -> bool:
    """True when ``value`` is a live array actually partitioned across devices.

    Placement truth, not spec truth: a state whose rule degraded to
    replication (no mesh at construction, indivisible leading dim) answers
    False, so consumers (the packed gather's skip, the restore fold) follow
    what the buffers really are. Mesh-replicated arrays (``PartitionSpec()``
    over the mesh) are NOT sharded — every device holds the whole value and
    the host can read it like any single-device array.
    """
    sharding = getattr(value, "sharding", None)
    if sharding is None:
        return False
    try:
        return not sharding.is_fully_replicated and len(sharding.device_set) > 1
    except Exception:  # noqa: BLE001 — exotic sharding types read as replicated
        return False


def spans_processes(value: Any) -> bool:
    """Whether ``value``'s placement covers devices of more than one process.

    The multi-host safety predicate: a sharded state whose mesh spans every
    process IS globally synced by its in-graph collectives, so skipping the
    host gather is exact; a sharded state on a process-LOCAL mesh in a
    multi-process world only folded local contributions — the sync driver
    warns loudly instead of silently serving partial totals.
    """
    sharding = getattr(value, "sharding", None)
    if sharding is None:
        return False
    try:
        return len({d.process_index for d in sharding.device_set}) > 1
    except Exception:  # noqa: BLE001 — exotic device types read as local
        return False


def partition_dim0(spec: Any, value: Any = None):
    """Resolve a dim-0 partition rule to a ``NamedSharding``, or ``None``.

    ``None`` (replicate) when: no active mesh, no value to inspect, a scalar
    value, or a leading dim the mesh axis does not divide evenly (JAX's
    ``device_put`` requires divisibility; padding a *state* would corrupt fold
    semantics, so the rule degrades instead — recorded as a ``shard.fallback``
    event, since an active mesh failing to shard is an operator-visible fact).
    """
    mesh = metric_mesh()
    if mesh is None or value is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec

    shape = tuple(getattr(value, "shape", ()))
    n = int(mesh.shape[STATE_AXIS])
    if not shape or shape[0] % n != 0:
        _diag.record(
            "shard.fallback", "sharding",
            state=getattr(spec, "name", ""), rule=getattr(spec, "shard_rule", ""),
            reason="indivisible" if shape else "scalar", shape=shape, axis=n,
        )
        return None
    return NamedSharding(mesh, PartitionSpec(STATE_AXIS))


# ------------------------------------------------------------------ placement


def place_state(metric: Any, name: str, value: Any, spec: Any) -> Any:
    """``device_put`` one state onto its rule's resolved sharding (or no-op).

    The born-distributed entry point ``add_state`` calls: the registered
    default itself is placed, so the state never materializes unsharded and
    ``reset()`` restores the sharded default by reference. Counted in
    ``shard_states`` and recorded as a ``shard.place`` event.
    """
    from torchmetrics_tpu.engine import statespec as _statespec

    sharding = _statespec.resolve_shard_rule(spec, value)
    if sharding is None:
        return value
    import jax

    placed = jax.device_put(value, sharding)
    global _ever_placed
    _ever_placed = True
    _STATS.shard_states += 1
    _diag.record(
        "shard.place", type(metric).__name__,
        state=name, rule=spec.shard_rule, axis=axis_size(),
        shape=tuple(getattr(value, "shape", ())),
    )
    return placed


def reshard_states(metric: Any) -> int:
    """Re-apply the registered shard rules to a metric's live states.

    The restore-side half of born-distributed: host round-trips
    (``load_state_dict``, unpickling, ``restore_resharded``) hand back
    single-device arrays, and this walks the spec registry and ``device_put``s
    every rule-carrying state — live value, registered default, and any
    compensation residual — back onto the resolved sharding. A no-op (returns
    0) when no mesh is active or every rule resolves to replication.
    """
    specs = metric.__dict__.get("_state_specs") or {}
    if not specs or metric_mesh() is None:
        return 0
    from torchmetrics_tpu.engine import statespec as _statespec

    import jax

    placed = 0
    residuals = metric.__dict__.get("_comp_residuals") or {}
    for name, spec in specs.items():
        if getattr(spec, "shard_rule", "replicate") == "replicate":
            continue
        for holder, getter, setter in (
            ("state", lambda: getattr(metric, name, None),
             lambda v: setattr(metric, name, v)),
            ("default", lambda: metric._defaults.get(name),
             lambda v: metric._defaults.__setitem__(name, v)),
            ("residual", lambda: residuals.get(name),
             lambda v: residuals.__setitem__(name, v)),
        ):
            value = getter()
            if value is None or isinstance(value, list) or not hasattr(value, "shape"):
                continue
            sharding = _statespec.resolve_shard_rule(spec, value)
            if sharding is None or getattr(value, "sharding", None) == sharding:
                continue
            setter(jax.device_put(value, sharding))
            placed += 1
    if placed:
        global _ever_placed
        _ever_placed = True
        _STATS.shard_states += placed
        _diag.record("shard.reshard", type(metric).__name__, placed=placed, axis=axis_size())
    return placed


# ------------------------------------------------------------------ engine glue


def state_out_shardings(example_state: Any) -> Optional[Any]:
    """``out_shardings`` pytree for a compiled step over ``example_state``.

    ``None`` when no leaf is partitioned (the common case — ``jax.jit`` keeps
    its default placement behavior, byte-identical to pre-sharding builds).
    Otherwise a matching pytree carrying each partitioned leaf's live
    ``NamedSharding`` and ``None`` (unspecified) for everything else — riders
    and scalar states come back mesh-replicated, sharded states come back
    sharded, and the executable lowers as one SPMD program whose cross-shard
    reductions are in-graph ``psum``/``psum_scatter``.
    """
    import jax

    if not any(is_sharded(v) for v in jax.tree_util.tree_leaves(example_state)):
        return None
    return jax.tree_util.tree_map(
        lambda v: v.sharding if is_sharded(v) else None, example_state
    )


def placement_token(state: Any) -> str:
    """Cache-key component naming a state pytree's device placement.

    Single-device pytrees yield the bare device string (the pre-sharding
    token, so warm caches key identically to older builds). Partitioned
    leaves append their ``PartitionSpec`` + sorted device ids: a state
    re-placed onto a different mesh or spec — or gathered back to one device
    — keys a fresh executable instead of dispatching a stale one compiled for
    the old placement (AOT executables are pinned to their example shardings).

    Hot-path cost: this runs inside the per-step dispatch key build, so until
    the process has placed at least one state distributed it short-circuits
    to the first leaf's device string — the exact pre-sharding token at the
    exact pre-sharding O(1) cost. Once sharding is live (a one-way latch:
    even a later gather-back-to-one-device must re-key), the full per-leaf
    walk applies.
    """
    import jax

    if not _ever_placed:
        for leaf in jax.tree_util.tree_leaves(state):
            try:
                return str(next(iter(leaf.devices())))
            except Exception:  # noqa: BLE001 — abstract/deleted leaves carry no device
                break
        return ""

    first = ""
    parts = []
    for leaf in jax.tree_util.tree_leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            continue
        if not first:
            try:
                first = str(next(iter(leaf.devices())))
            except Exception:  # noqa: BLE001 — deleted/abstract leaves carry no device
                continue
        if is_sharded(leaf):
            ids = ",".join(str(d.id) for d in sorted(sharding.device_set, key=lambda d: d.id))
            parts.append(f"{sharding.spec}@{ids}")
    return first if not parts else first + "|" + ";".join(parts)


def shard_report() -> Dict[str, Any]:
    """Process-wide sharding facts for telemetry/bench evidence."""
    mesh = metric_mesh()
    return {
        "active": mesh is not None,
        "axis_size": axis_size(),
        "devices": [] if mesh is None else [int(d.id) for d in mesh.devices.flat],
        "shard_states": _STATS.shard_states,
    }
