"""SPMD sharded-state engine — the mesh/pjit layer that makes ``shard_rule`` real.

Every state used to be replicated per rank: one whole copy per device, synced
at epoch end by the packed host gather. That caps state size at one device's
HBM — a million-class confusion matrix or vocab-level per-class counters are
simply unrepresentable. This module turns the ``StateSpec.shard_rule`` slot
(PR 11's landing pad, ``engine/statespec.py``) into actual placement:

- **Mesh manager.** :func:`metric_mesh` resolves the process-wide
  ``jax.sharding.Mesh`` the shard rules partition over — a 1-D mesh with the
  named axis :data:`STATE_AXIS` (``"state"``), built over the local devices
  (CPU multi-device via ``--xla_force_host_platform_device_count`` for
  tests/bench, real chips in production). Activation is explicit:
  :func:`mesh_context` / :func:`set_mesh` scoped overrides, or the
  ``TORCHMETRICS_TPU_SHARD`` env var (``"1"``/``"all"`` = every local device,
  an integer N = the first N; invalid values FAIL LOUD per the PR-7 env
  contract). With no active mesh every rule resolves to ``None`` and nothing
  changes — replicated state, today's semantics.

- **Born distributed.** ``Metric.add_state`` resolves the registered spec's
  rule through :func:`~torchmetrics_tpu.engine.statespec.resolve_shard_rule`
  and ``device_put``s the default onto the resolved ``NamedSharding`` — the
  state (and its registered default, so ``reset()`` keeps the placement) never
  materializes unsharded. A rule that cannot partition the value (no active
  mesh; a leading dim the mesh axis does not divide) degrades to replication,
  recorded as a ``shard.fallback`` event when a mesh was active.

- **SPMD executables.** The compiled-step engines (``engine/compiled.py``,
  ``engine/scan.py``, ``engine/fusion.py``) pass
  :func:`state_out_shardings` as ``jax.jit(..., out_shardings=...)`` and key
  their caches on :func:`placement_token`, so the donated update/scan
  executables lower as SPMD programs: the batch contribution is computed and
  scattered shard-locally, GSPMD inserts the in-graph ``psum`` /
  ``psum_scatter`` collectives the partitioning needs, and a re-placed state
  compiles a fresh signature instead of colliding with the replicated one.

- **Sync is in-graph.** A live-sharded state is *global by construction* —
  the SPMD program already folded every device's contribution through XLA
  collectives — so the packed host gather (``parallel/packing.py``) skips it
  entirely (``gather_skipped``; additive folds counted as ``psum_syncs``).
  Gathering it through the host would both defeat the point and, on a mesh
  spanning processes, read buffers this host cannot address.

- **Lifecycle.** Riders (``__sentinel__``/``__quarantine__`` scalars stay
  replicated; the ``__compensation__`` residual inherits its value's sharding
  via ``zeros_like``), scan carries, quarantine rollback selects, snapshot
  copies and clones all preserve placement because JAX propagates shardings
  through eager ops and ``deepcopy``. The paths that genuinely round-trip
  through host numpy — ``state_dict``/``load_state_dict``, pickling,
  ``restore_resharded`` — re-apply the registered rules via
  :func:`reshard_states` on restore.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Dict, Generator, Optional, Sequence, Tuple

from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.engine.stats import EngineStats
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "DATA_AXIS",
    "MULTIHOST_ENV_VAR",
    "SHARD_ENV_VAR",
    "STATE_AXIS",
    "apply_partition_rule",
    "axis_size",
    "build_mesh",
    "data_axis_size",
    "ensure_multihost",
    "is_sharded",
    "match_partition_rule",
    "mesh_context",
    "metric_mesh",
    "multihost_spec",
    "partition_dim0",
    "partition_rules_context",
    "place_state",
    "placement_token",
    "reshard_states",
    "set_mesh",
    "set_partition_rules",
    "shard_batch",
    "sharding_enabled",
    "state_out_shardings",
]

SHARD_ENV_VAR = "TORCHMETRICS_TPU_SHARD"
MULTIHOST_ENV_VAR = "TORCHMETRICS_TPU_MULTIHOST"

#: the named mesh axis shard rules partition over — ``"class_axis"`` /
#: ``"row_sharded"`` split a state's leading dim across it
STATE_AXIS = "state"

#: the named batch axis of the 2-D ``(data, state)`` mesh: update inputs shard
#: over it (:func:`shard_batch`) and, when it is live, the epoch engine lowers
#: the cross-rank fold of replicated states onto it as in-graph
#: ``psum``/``pmax``/``pmin``/``all_gather`` (engine/epoch.py) instead of the
#: host packed gather
DATA_AXIS = "data"

_UNSET = object()
_mesh_override: Any = _UNSET

# module-level stats block: mesh placement is a process-wide fact, not a
# per-engine property — one EngineStats joins the weak registry so
# engine_report()/telemetry aggregate it (the module global keeps it alive)
_STATS = EngineStats("sharding")

# set the first time any state is actually placed distributed; the per-step
# placement-token walk short-circuits to the pre-sharding O(1) token until
# then, so processes that never shard pay one bool check per dispatch
_ever_placed = False


# ------------------------------------------------------------------ mesh policy


def build_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[Any]] = None,
    data: Optional[int] = None,
):
    """A :class:`jax.sharding.Mesh` for metric state — 1-D or 2-D.

    With ``data`` unset (or 1) this is the PR-12 1-D mesh with the single
    named axis ``"state"`` — byte-identical policy, shapes, and errors, so
    every existing cache key and test pin survives. With ``data >= 2`` the
    device list reshapes to ``(data, state)`` under the named axes
    ``("data", "state")``: states partition over ``"state"``, update inputs
    and the epoch engine's in-graph cross-rank fold ride ``"data"``.

    ``devices`` wins when given; otherwise the first ``data * n_devices`` of
    the GLOBAL device set (all of them when ``n_devices`` is ``None``) —
    identical to the local set in a single process, and the only placement
    whose in-graph collectives actually span the world in a multi-process one
    (a process-local mesh there folds only local contributions; the sync
    driver warns when it sees that). Fewer than 2 devices total is a loud
    error — a 1-device "mesh" would silently demote every rule to replication
    while the operator believes sharding is on.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    dsize = 1 if data is None else data
    if not isinstance(dsize, int) or isinstance(dsize, bool) or dsize < 1:
        raise TorchMetricsUserError(
            f"the 'data' mesh axis needs an integer size >= 1 (got {data!r})"
        )
    ensure_multihost()
    if devices is None:
        world = jax.devices()
        if n_devices is not None:
            min_state = 2 if dsize == 1 else 1
            if not isinstance(n_devices, int) or isinstance(n_devices, bool) or n_devices < min_state:
                raise TorchMetricsUserError(
                    f"a state mesh needs an integer device count >= 2 (got {n_devices!r})"
                    if dsize == 1
                    else f"the 'state' axis of a (data, state) mesh needs an"
                    f" integer size >= 1 (got {n_devices!r})"
                )
            if dsize * n_devices > len(world):
                raise TorchMetricsUserError(
                    f"requested a {dsize}x{n_devices} (data, state) mesh but only"
                    f" {len(world)} devices exist (CPU tests: raise"
                    " --xla_force_host_platform_device_count)"
                    if dsize > 1
                    else f"requested a {n_devices}-device state mesh but only"
                    f" {len(world)} devices exist (CPU tests: raise"
                    " --xla_force_host_platform_device_count)"
                )
            world = world[: dsize * n_devices]
        elif dsize > 1:
            if len(world) % dsize != 0:
                raise TorchMetricsUserError(
                    f"a data axis of {dsize} does not divide the {len(world)}-device"
                    " world evenly; pass an explicit state size"
                    " (e.g. mesh_context(data=2, state=2))"
                )
            world = world[: len(world)]
        devices = world
    if len(devices) < 2:
        raise TorchMetricsUserError(
            f"a state mesh needs >= 2 devices (got {len(devices)}); with one"
            " device every shard rule is a no-op — leave sharding off instead"
        )
    if dsize > 1:
        if len(devices) % dsize != 0:
            raise TorchMetricsUserError(
                f"a data axis of {dsize} does not divide the {len(devices)}-device"
                " list evenly — a (data, state) mesh must be rectangular"
            )
        # tmlint: disable=TM101 — `devices` is a host list of Device objects
        return Mesh(np.asarray(devices).reshape(dsize, -1), (DATA_AXIS, STATE_AXIS))
    # tmlint: disable=TM101 — `devices` is a host list of Device objects
    return Mesh(np.asarray(devices), (STATE_AXIS,))


def _env_mesh():
    """The mesh the ``TORCHMETRICS_TPU_SHARD`` env var names, or ``None``.

    ``""``/``"0"``/``"off"`` = off; ``"1"``/``"on"``/``"all"`` = every local
    device; an integer N >= 2 = the first N. Anything else fails loud (the
    PR-7 env contract: a typo must not silently change placement semantics).
    """
    raw = os.environ.get(SHARD_ENV_VAR, "").strip().lower()
    if raw in ("", "0", "off"):
        return None
    if raw in ("1", "on", "all"):
        return build_mesh()
    if "x" in raw:
        # 2-D "DxS" spec: data x state (e.g. "2x4" = 2-row data axis over a
        # 4-device state axis). "1xS" is exactly the 1-D S-device mesh.
        head, _, tail = raw.partition("x")
        try:
            dn, sn = int(head), int(tail)
        except ValueError:
            raise TorchMetricsUserError(
                f"{SHARD_ENV_VAR}={raw!r} is not a valid mesh spec (expected"
                " unset/'0'/'off', '1'/'on'/'all', an integer N >= 2, or a 2-D"
                " 'DxS' data-by-state spec such as '2x4')"
            ) from None
        if dn < 1 or sn < 1 or dn * sn < 2:
            raise TorchMetricsUserError(
                f"{SHARD_ENV_VAR}={raw!r} names a {dn}x{sn} mesh — both axes"
                " must be >= 1 and the mesh must span >= 2 devices"
            )
        return build_mesh(sn, data=dn) if dn > 1 else build_mesh(sn)
    try:
        n = int(raw)
    except ValueError:
        raise TorchMetricsUserError(
            f"{SHARD_ENV_VAR}={raw!r} is not a valid state-mesh size (expected"
            " unset/'0'/'off', '1'/'on'/'all', an integer N >= 2, or a 2-D"
            " 'DxS' data-by-state spec such as '2x4')"
        ) from None
    return build_mesh(n)


def metric_mesh():
    """The active state mesh, or ``None`` (sharding off — replicated state)."""
    if _mesh_override is not _UNSET:
        return _mesh_override
    return _env_mesh()


def set_mesh(mesh: Any = None, *, data: Optional[int] = None, state: Optional[int] = None) -> None:
    """Force the state mesh process-wide.

    Accepts a ready :class:`jax.sharding.Mesh`, an integer device count,
    ``True`` (all local devices), or ``False`` (force sharding OFF regardless
    of the env var — the same spelling :func:`mesh_context` accepts); ``None``
    restores env-var resolution. ``data=``/``state=`` build a 2-D
    ``(data, state)`` mesh instead (``state=None`` spreads the remaining
    devices); they are mutually exclusive with a positional ``mesh``.
    """
    global _mesh_override
    if data is not None or state is not None:
        if mesh is not None and mesh is not True:
            raise TorchMetricsUserError(
                "pass either a mesh/device-count or data=/state= axis sizes, not both"
            )
        _mesh_override = build_mesh(state, data=data)
        return
    if mesh is None:
        _mesh_override = _UNSET
    elif mesh is False:
        # bool before int: isinstance(False, int) is True, and the build_mesh
        # size check would raise a baffling "got False" instead of disabling
        _mesh_override = None
    elif mesh is True:
        _mesh_override = build_mesh()
    elif isinstance(mesh, int):
        _mesh_override = build_mesh(mesh)
    else:
        _mesh_override = mesh


@contextmanager
def mesh_context(
    mesh: Any = True, *, data: Optional[int] = None, state: Optional[int] = None
) -> Generator[Any, None, None]:
    """Scoped state-mesh activation (tests, benches, serving loops).

    ``mesh`` as in :func:`set_mesh` (``False`` forces sharding OFF inside the
    scope regardless of the env var); ``mesh_context(data=N, state=M)``
    activates a 2-D ``(data, state)`` mesh instead. Yields the active mesh
    (or ``None``). Placement happens at ``add_state`` /
    :func:`reshard_states` time — states born inside the scope stay sharded
    after it exits (arrays are committed); only NEW placements see the
    restored policy.
    """
    global _mesh_override
    prev = _mesh_override
    if data is not None or state is not None:
        set_mesh(None if mesh is True else mesh, data=data, state=state)
    else:
        set_mesh(mesh)
    try:
        yield metric_mesh()
    finally:
        _mesh_override = prev


def sharding_enabled() -> bool:
    """Whether an active mesh makes shard rules resolve to real placements."""
    return metric_mesh() is not None


# ------------------------------------------------------------------ multi-host

# one-way latch: jax.distributed.initialize is once-per-process by contract
_multihost_initialized = False


def multihost_spec() -> Optional[Dict[str, Any]]:
    """Parse ``TORCHMETRICS_TPU_MULTIHOST`` — the pod-slice formation knob.

    ``""``/``"0"``/``"off"`` = off (``None``); ``"1"``/``"on"``/``"auto"`` =
    auto-detected coordinator (``jax.distributed.initialize()`` with no
    arguments — the TPU-pod default, where the runtime publishes the
    coordinator); an explicit ``"host:port:num_processes:process_id"`` spec
    pins all three for CPU/GPU clusters and subprocess tests. Anything else
    fails loud (the PR-7 env contract: a typo must not silently leave a pod
    un-formed while the operator believes multi-host sync is on).
    """
    raw = os.environ.get(MULTIHOST_ENV_VAR, "").strip()
    low = raw.lower()
    if low in ("", "0", "off"):
        return None
    if low in ("1", "on", "auto"):
        return {}
    parts = raw.split(":")
    if len(parts) == 4:
        try:
            return {
                "coordinator_address": f"{parts[0]}:{int(parts[1])}",
                "num_processes": int(parts[2]),
                "process_id": int(parts[3]),
            }
        except ValueError:
            pass
    raise TorchMetricsUserError(
        f"{MULTIHOST_ENV_VAR}={raw!r} is not a valid multi-host spec (expected"
        " unset/'0'/'off', '1'/'on'/'auto', or 'host:port:num_processes:process_id')"
    )


def ensure_multihost() -> bool:
    """Form the real pod slice the knob names (idempotent; False = knob off).

    Called by :func:`build_mesh` before it reads ``jax.devices()``, so a mesh
    built under ``TORCHMETRICS_TPU_MULTIHOST`` spans the GLOBAL device set of
    a genuinely-initialized multi-process world — the emulated-world tests
    gain a real pod-slice execution mode by flipping one env var. Failures
    from ``jax.distributed.initialize`` propagate (a half-formed world must
    not silently degrade to single-process semantics).
    """
    global _multihost_initialized
    spec = multihost_spec()
    if spec is None:
        return False
    if _multihost_initialized:
        return True
    import jax

    already = False
    try:
        already = bool(jax.distributed.is_initialized())
    except AttributeError:  # older jax: probe the client on the global state
        state = getattr(jax.distributed, "global_state", None)
        already = getattr(state, "client", None) is not None
    if not already:
        jax.distributed.initialize(**spec)
    _multihost_initialized = True
    _diag.record(
        "multihost.init", "sharding",
        processes=int(jax.process_count()), process=int(jax.process_index()),
        explicit=bool(spec),
    )
    return True


def axis_size() -> int:
    """Devices along the ``"state"`` axis of the active mesh (1 when off)."""
    mesh = metric_mesh()
    return 1 if mesh is None else int(dict(mesh.shape).get(STATE_AXIS, 1))


def data_axis_size() -> int:
    """Devices along the ``"data"`` axis of the active mesh (1 when off/1-D).

    A live data axis (>= 2) is the epoch engine's trigger to lower the
    cross-rank fold of replicated states onto the mesh as in-graph
    collectives (``engine/epoch.py``) instead of the host packed gather.
    """
    mesh = metric_mesh()
    return 1 if mesh is None else int(dict(mesh.shape).get(DATA_AXIS, 1))


def shard_batch(x: Any) -> Any:
    """``device_put`` dim 0 of an update input over the ``"data"`` mesh axis.

    The input-side half of the 2-D story: states partition over ``"state"``,
    per-batch update inputs shard over ``"data"`` so the SPMD update
    executable computes each data row's contribution shard-locally. A no-op
    (the value is returned untouched) when no data axis is live or the
    leading dim is not divisible by it — inputs are transient, so degrading
    silently here is exact, unlike state placement which records.
    """
    mesh = metric_mesh()
    n = data_axis_size()
    if mesh is None or n < 2:
        return x
    shape = tuple(getattr(x, "shape", ()))
    if not shape or shape[0] % n != 0:
        return x
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.device_put(x, NamedSharding(mesh, PartitionSpec(DATA_AXIS)))


# ------------------------------------------------------------------ predicates


def is_sharded(value: Any) -> bool:
    """True when ``value`` is a live array actually partitioned across devices.

    Placement truth, not spec truth: a state whose rule degraded to
    replication (no mesh at construction, indivisible leading dim) answers
    False, so consumers (the packed gather's skip, the restore fold) follow
    what the buffers really are. Mesh-replicated arrays (``PartitionSpec()``
    over the mesh) are NOT sharded — every device holds the whole value and
    the host can read it like any single-device array.
    """
    sharding = getattr(value, "sharding", None)
    if sharding is None:
        return False
    try:
        return not sharding.is_fully_replicated and len(sharding.device_set) > 1
    except Exception:  # noqa: BLE001 — exotic sharding types read as replicated
        return False


def spans_processes(value: Any) -> bool:
    """Whether ``value``'s placement covers devices of more than one process.

    The multi-host safety predicate: a sharded state whose mesh spans every
    process IS globally synced by its in-graph collectives, so skipping the
    host gather is exact; a sharded state on a process-LOCAL mesh in a
    multi-process world only folded local contributions — the sync driver
    warns loudly instead of silently serving partial totals.
    """
    sharding = getattr(value, "sharding", None)
    if sharding is None:
        return False
    try:
        return len({d.process_index for d in sharding.device_set}) > 1
    except Exception:  # noqa: BLE001 — exotic device types read as local
        return False


def _record_degrade(spec: Any, reason: str, shape: Tuple[int, ...], axis: int) -> None:
    """One degrade-to-replication: counted (``shard_degrades``) AND recorded.

    An active mesh failing to shard is an operator-visible fact — the event
    narrates it, the counter exports it (``tm_tpu_shard_degrades_total``), so
    a fleet where "sharding is on" but rules quietly replicate is discoverable
    from a scrape, not only from a flight-recorder dump.
    """
    _STATS.shard_degrades += 1
    _diag.record(
        "shard.fallback", "sharding",
        state=getattr(spec, "name", ""), rule=getattr(spec, "shard_rule", ""),
        reason=reason, shape=shape, axis=axis,
    )


def partition_dim0(spec: Any, value: Any = None):
    """Resolve a dim-0 partition rule to a ``NamedSharding``, or ``None``.

    ``None`` (replicate) when: no active mesh, no value to inspect, a scalar
    value, a mesh with no live ``"state"`` axis (a data-only 2-D mesh), or a
    leading dim the state axis does not divide evenly (JAX's ``device_put``
    requires divisibility; padding a *state* would corrupt fold semantics, so
    the rule degrades instead — recorded as a ``shard.fallback`` event and
    counted in ``shard_degrades``, since an active mesh failing to shard is
    an operator-visible fact). On a 2-D mesh the resolved sharding partitions
    dim 0 over ``"state"`` and replicates over ``"data"`` — exactly the
    placement the in-graph epoch fold expects.
    """
    mesh = metric_mesh()
    if mesh is None or value is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec

    shape = tuple(getattr(value, "shape", ()))
    n = int(dict(mesh.shape).get(STATE_AXIS, 1))
    if not shape or n < 2 or shape[0] % n != 0:
        reason = "scalar" if not shape else ("no-state-axis" if n < 2 else "indivisible")
        _record_degrade(spec, reason, shape, n)
        return None
    return NamedSharding(mesh, PartitionSpec(STATE_AXIS))


# ------------------------------------------------------------------ rule table

# per-state-name partition rules (regex -> PartitionSpec axes), consulted by
# ``statespec.resolve_shard_rule`` BEFORE the named SHARD_RULES entry — the
# operator-side override channel: shard an out-of-tree metric's states without
# touching its class declarations, or pin one state of a declared family to a
# different layout. Empty by default (zero cost until set).
_partition_rules: Tuple[Tuple[Any, Tuple[Optional[str], ...]], ...] = ()


def _compile_rules(rules: Optional[Sequence[Tuple[str, Any]]]):
    import re

    from jax.sharding import PartitionSpec

    compiled = []
    for entry in rules or ():
        try:
            pattern, spec = entry
        except (TypeError, ValueError):
            raise TorchMetricsUserError(
                f"partition rules are (regex, spec) pairs (got {entry!r})"
            ) from None
        try:
            rx = re.compile(pattern)
        except re.error as exc:
            raise TorchMetricsUserError(
                f"invalid partition-rule regex {pattern!r}: {exc}"
            ) from None
        if spec is None:
            axes: Tuple[Optional[str], ...] = ()
        elif isinstance(spec, str):
            axes = (spec,)
        elif isinstance(spec, PartitionSpec):
            axes = tuple(spec)
        elif isinstance(spec, (tuple, list)):
            axes = tuple(spec)
        else:
            raise TorchMetricsUserError(
                f"partition rule {pattern!r} names an unsupported spec {spec!r}"
                " (expected None, an axis name, a tuple of axis names/None, or"
                " a jax.sharding.PartitionSpec)"
            )
        for ax in axes:
            if ax is not None and ax not in (DATA_AXIS, STATE_AXIS):
                raise TorchMetricsUserError(
                    f"partition rule {pattern!r} names unknown mesh axis {ax!r}"
                    f" (known axes: {DATA_AXIS!r}, {STATE_AXIS!r})"
                )
        compiled.append((rx, axes))
    return tuple(compiled)


def set_partition_rules(rules: Optional[Sequence[Tuple[str, Any]]]) -> None:
    """Install the process-wide per-state-name partition-rule table.

    ``rules`` is an ordered sequence of ``(regex, spec)`` pairs; the first
    regex that matches a state's qualified name (``"<MetricClass>/<state>"``
    when the owner is known, the bare state name otherwise — matching is
    ``re.search``, so an unanchored bare-name pattern matches both forms)
    wins. ``spec`` names the per-dim mesh axes: an axis name string (dim 0),
    a tuple like ``("state", None)`` / ``("data",)``, a ready
    ``jax.sharding.PartitionSpec``, or ``None`` to force replication.
    Validation is eager and loud (the PR-7 env contract's spirit): a bad
    regex or an unknown axis raises at install, never at first placement.
    ``None``/``()`` clears the table.
    """
    global _partition_rules
    _partition_rules = _compile_rules(rules)


@contextmanager
def partition_rules_context(
    rules: Optional[Sequence[Tuple[str, Any]]],
) -> Generator[None, None, None]:
    """Scoped partition-rule table (tests, benches) — see :func:`set_partition_rules`."""
    global _partition_rules
    prev = _partition_rules
    _partition_rules = _compile_rules(rules)
    try:
        yield
    finally:
        _partition_rules = prev


def partition_rules_active() -> bool:
    """Whether any per-state-name partition rule is installed (cheap gate)."""
    return bool(_partition_rules)


def match_partition_rule(name: str, owner: str = ""):
    """First table entry matching ``owner/name`` — ``(pattern, axes)`` or ``None``."""
    if not _partition_rules:
        return None
    qualified = f"{owner}/{name}" if owner else name
    for rx, axes in _partition_rules:
        if rx.search(qualified):
            return (rx.pattern, axes)
    return None


def apply_partition_rule(spec: Any, value: Any, axes: Sequence[Optional[str]]):
    """Resolve a table entry's per-dim axes to a ``NamedSharding`` (or ``None``).

    Per-dim divisibility-checked: a dim whose named mesh axis is absent
    (< 2 devices), out of the value's rank, or does not divide evenly
    degrades to ``None`` (replicated along that dim) — recorded once per
    resolution via ``shard.fallback`` + ``shard_degrades``, like the named
    rules. A fully-degraded (or explicitly replicating) entry returns
    ``None``.
    """
    mesh = metric_mesh()
    if mesh is None or value is None:
        return None
    if not any(a is not None for a in axes):
        return None  # explicit replicate entry — intent, not degradation
    from jax.sharding import NamedSharding, PartitionSpec

    shape = tuple(getattr(value, "shape", ()))
    if not shape:
        _record_degrade(spec, "scalar", shape, 0)
        return None
    sizes = dict(mesh.shape)
    resolved = []
    degraded_reason = ""
    for i, ax in enumerate(axes):
        if ax is None:
            resolved.append(None)
            continue
        n = int(sizes.get(ax, 1))
        if n < 2:
            degraded_reason = degraded_reason or "axis-missing"
            resolved.append(None)
        elif i >= len(shape):
            degraded_reason = degraded_reason or "rank-mismatch"
            resolved.append(None)
        elif shape[i] % n != 0:
            degraded_reason = degraded_reason or "indivisible"
            resolved.append(None)
        else:
            resolved.append(ax)
    if degraded_reason:
        _record_degrade(spec, degraded_reason, shape, int(sizes.get(STATE_AXIS, 1)))
    while resolved and resolved[-1] is None:
        resolved.pop()
    if not any(resolved):
        return None
    return NamedSharding(mesh, PartitionSpec(*resolved))


# ------------------------------------------------------------------ placement


def place_state(metric: Any, name: str, value: Any, spec: Any) -> Any:
    """``device_put`` one state onto its rule's resolved sharding (or no-op).

    The born-distributed entry point ``add_state`` calls: the registered
    default itself is placed, so the state never materializes unsharded and
    ``reset()`` restores the sharded default by reference. Counted in
    ``shard_states`` and recorded as a ``shard.place`` event.
    """
    from torchmetrics_tpu.engine import statespec as _statespec

    sharding = _statespec.resolve_shard_rule(spec, value, owner=type(metric).__name__)
    if sharding is None:
        return value
    import jax

    placed = jax.device_put(value, sharding)
    global _ever_placed
    _ever_placed = True
    _STATS.shard_states += 1
    _diag.record(
        "shard.place", type(metric).__name__,
        state=name, rule=spec.shard_rule, axis=axis_size(),
        shape=tuple(getattr(value, "shape", ())),
    )
    return placed


def reshard_states(metric: Any) -> int:
    """Re-apply the registered shard rules to a metric's live states.

    The restore-side half of born-distributed: host round-trips
    (``load_state_dict``, unpickling, ``restore_resharded``) hand back
    single-device arrays, and this walks the spec registry and ``device_put``s
    every rule-carrying state — live value, registered default, and any
    compensation residual — back onto the resolved sharding. A no-op (returns
    0) when no mesh is active or every rule resolves to replication.
    """
    specs = metric.__dict__.get("_state_specs") or {}
    if not specs or metric_mesh() is None:
        return 0
    from torchmetrics_tpu.engine import statespec as _statespec

    import jax

    placed = 0
    owner = type(metric).__name__
    residuals = metric.__dict__.get("_comp_residuals") or {}
    for name, spec in specs.items():
        if (
            getattr(spec, "shard_rule", "replicate") == "replicate"
            and match_partition_rule(name, owner) is None
        ):
            continue
        for holder, getter, setter in (
            ("state", lambda: getattr(metric, name, None),
             lambda v: setattr(metric, name, v)),
            ("default", lambda: metric._defaults.get(name),
             lambda v: metric._defaults.__setitem__(name, v)),
            ("residual", lambda: residuals.get(name),
             lambda v: residuals.__setitem__(name, v)),
        ):
            value = getter()
            if value is None or isinstance(value, list) or not hasattr(value, "shape"):
                continue
            sharding = _statespec.resolve_shard_rule(spec, value, owner=owner)
            if sharding is None or getattr(value, "sharding", None) == sharding:
                continue
            setter(jax.device_put(value, sharding))
            placed += 1
    if placed:
        global _ever_placed
        _ever_placed = True
        _STATS.shard_states += placed
        _diag.record("shard.reshard", type(metric).__name__, placed=placed, axis=axis_size())
    return placed


# ------------------------------------------------------------------ engine glue


def state_out_shardings(example_state: Any) -> Optional[Any]:
    """``out_shardings`` pytree for a compiled step over ``example_state``.

    ``None`` when no leaf is partitioned (the common case — ``jax.jit`` keeps
    its default placement behavior, byte-identical to pre-sharding builds).
    Otherwise a matching pytree carrying each partitioned leaf's live
    ``NamedSharding`` and ``None`` (unspecified) for everything else — riders
    and scalar states come back mesh-replicated, sharded states come back
    sharded, and the executable lowers as one SPMD program whose cross-shard
    reductions are in-graph ``psum``/``psum_scatter``.
    """
    import jax

    if not any(is_sharded(v) for v in jax.tree_util.tree_leaves(example_state)):
        return None
    return jax.tree_util.tree_map(
        lambda v: v.sharding if is_sharded(v) else None, example_state
    )


def placement_token(state: Any) -> str:
    """Cache-key component naming a state pytree's device placement.

    Single-device pytrees yield the bare device string (the pre-sharding
    token, so warm caches key identically to older builds). Partitioned
    leaves append their ``PartitionSpec`` + sorted device ids: a state
    re-placed onto a different mesh or spec — or gathered back to one device
    — keys a fresh executable instead of dispatching a stale one compiled for
    the old placement (AOT executables are pinned to their example shardings).

    Hot-path cost: this runs inside the per-step dispatch key build, so until
    the process has placed at least one state distributed it short-circuits
    to the first leaf's device string — the exact pre-sharding token at the
    exact pre-sharding O(1) cost. Once sharding is live (a one-way latch:
    even a later gather-back-to-one-device must re-key), the full per-leaf
    walk applies.
    """
    import jax

    if not _ever_placed:
        for leaf in jax.tree_util.tree_leaves(state):
            try:
                return str(next(iter(leaf.devices())))
            except Exception:  # noqa: BLE001 — abstract/deleted leaves carry no device
                break
        return ""

    first = ""
    parts = []
    for leaf in jax.tree_util.tree_leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            continue
        if not first:
            try:
                first = str(next(iter(leaf.devices())))
            except Exception:  # noqa: BLE001 — deleted/abstract leaves carry no device
                continue
        if is_sharded(leaf):
            ids = ",".join(str(d.id) for d in sorted(sharding.device_set, key=lambda d: d.id))
            parts.append(f"{sharding.spec}@{ids}")
    return first if not parts else first + "|" + ";".join(parts)


def shard_report() -> Dict[str, Any]:
    """Process-wide sharding facts for telemetry/bench evidence."""
    mesh = metric_mesh()
    return {
        "active": mesh is not None,
        "axis_size": axis_size(),
        "data_axis_size": data_axis_size(),
        "devices": [] if mesh is None else [int(d.id) for d in mesh.devices.flat],
        "shard_states": _STATS.shard_states,
        "shard_degrades": _STATS.shard_degrades,
        "partition_rules": len(_partition_rules),
    }
