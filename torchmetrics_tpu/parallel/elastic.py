"""Elastic resharding checkpoint/restore — packed state across world resizes.

Production pods lose and gain ranks; a checkpoint written by an N-rank world
must restore into an M-rank world without corrupting the fold semantics every
``dist_reduce_fx`` encodes. This module provides exactly that:

- :func:`save_state_shard` — one **atomic** (``.tmp`` + ``os.replace``),
  **version-stamped**, **CRC-protected** ``.npz`` snapshot of this rank's
  local states (+ update count), tagged ``(rank, world_size)``. A crash
  mid-write leaves only a ``.tmp`` file, which restore ignores — the previous
  complete snapshot stays authoritative.
- :func:`restore_resharded` — loads the *full shard set* of the saved world
  and restores it into a (possibly different) ``world_size``. The cross-shard
  fold is **re-planned and recompiled on restore** through the exact packed
  machinery the live sync uses (:class:`~torchmetrics_tpu.parallel.packing.
  PackedSyncPlan` + ``make_fold`` under ``jax.jit``), then split across the
  new world so a later M-rank packed sync reproduces the N-rank result
  bit-for-bit:

  =============  =========================================================
  ``sum``        new rank 0 carries the folded total, others zeros — the
                 M-rank sum re-produces it exactly
  ``mean``       the folded mean replicates to every rank (a mean of
                 identical values is itself) — exact for any M
  ``max/min``    the folded extremum replicates (idempotent fold) — exact
  ``cat``        concatenated rows split into contiguous chunks in rank
                 order — the M-rank concat re-produces the row order
  ``custom``/``none``  no algebra is known that survives a world resize —
                 :class:`SnapshotReshardError`, fail loud (same-world
                 restore of these states is fully supported)
  =============  =========================================================

- **Integrity is loud**: a corrupted shard (CRC mismatch, unreadable
  archive) raises :class:`SnapshotIntegrityError`; a snapshot written by a
  different layout version raises :class:`SnapshotVersionError` —
  deterministically, on every rank that attempts the restore. ``last_good``
  names a fallback shard set to restore instead (counted and recorded as a
  ``snapshot.fallback`` flight-recorder event) so a corrupted latest snapshot
  degrades to the previous one rather than to a crash loop.

Preemption-safe **continuous** snapshots build on the same primitives:

- :class:`SnapshotPolicy` — cadence (every N updates and/or every T seconds,
  ``TORCHMETRICS_TPU_SNAPSHOT_EVERY``: ``"500"`` = updates, ``"30s"`` =
  seconds).
- :class:`ContinuousSnapshotter` — drives :func:`save_state_shard` on the
  cadence into numbered sequences (``snap-000042.rank0-of-2.npz``), prunes
  old sequences per rank, and installs SIGTERM/SIGINT handlers that flush a
  FINAL shard before the process dies — a pod preemption between epoch-end
  checkpoints loses at most the in-flight batch, not the epoch.
- :func:`restore_latest` — walks the snapshot sequences newest-first and
  restores the first COMPLETE, integrity-clean set (a preemption that caught
  only some ranks mid-sequence degrades to the previous complete one — the
  last-good chain, automated).
"""

from __future__ import annotations

import os
import re
import signal as _signal
import time as _time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "SNAPSHOT_EVERY_ENV_VAR",
    "SNAPSHOT_VERSION",
    "ContinuousSnapshotter",
    "SnapshotIntegrityError",
    "SnapshotPolicy",
    "SnapshotReshardError",
    "SnapshotVersionError",
    "list_snapshots",
    "restore_latest",
    "restore_resharded",
    "save_state_shard",
    "shard_path",
    "state_fingerprint",
]

#: bump when the snapshot layout changes; mismatched snapshots fail loud
SNAPSHOT_VERSION = 1

_META_KEYS = ("__elastic_version__", "__rank__", "__world__", "__crc__")


class SnapshotIntegrityError(TorchMetricsUserError):
    """The snapshot is corrupt (CRC mismatch / unreadable / incomplete set)."""


class SnapshotVersionError(TorchMetricsUserError):
    """The snapshot was written by an incompatible layout version."""


class SnapshotReshardError(TorchMetricsUserError):
    """This state layout cannot be resharded into a different world size."""


def shard_path(base: str, rank: int, world_size: int) -> str:
    """Canonical per-rank shard filename under a common ``base``."""
    return f"{base}.rank{int(rank)}-of-{int(world_size)}.npz"


def _payload_crc(flat: Dict[str, np.ndarray]) -> int:
    """Order-independent digest over every payload entry's name/dtype/shape/bytes."""
    crc = 0
    for key in sorted(flat):
        if key in _META_KEYS:
            continue
        arr = np.ascontiguousarray(flat[key])
        header = f"{key}|{arr.dtype}|{arr.shape}|".encode()
        crc = zlib.crc32(arr.tobytes(), zlib.crc32(header, crc))
    return crc & 0xFFFFFFFF


def _collect_flat(metric: Any) -> Dict[str, np.ndarray]:
    """This rank's full state as a flat numpy dict (persistence forced on).

    The read rides the sanctioned ``snapshot-save`` boundary — persisting
    state to disk is a DECLARED host transfer, like the sync collectives.
    """
    from torchmetrics_tpu.utilities.checkpoint import (
        _restore_persistence,
        _snapshot_persistence,
        _to_saveable,
    )

    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    saved_flags = _snapshot_persistence(metric)
    try:
        metric.persistent(True)
        with transfer_allowed("snapshot-save"):
            flat = _to_saveable(metric.state_dict())
            # the materialization below is the ACTUAL device read — it must sit
            # inside the sanctioned boundary, not just the state_dict() walk
            return {k: np.asarray(v) for k, v in flat.items()}
    finally:
        _restore_persistence(metric, saved_flags)


def state_fingerprint(metric: Any) -> int:
    """Order-independent CRC of the metric's full persisted state.

    The same digest :func:`save_state_shard` stamps into a shard's payload —
    two metrics with byte-identical persisted state (values AND update count)
    fingerprint identically, so a snapshot→restore round-trip can be audited
    without re-reading the shard.
    """
    return _payload_crc(_collect_flat(metric))


# tmlint: boundary(snapshot-save) — the payload is already host numpy
# (_collect_flat materialized it under the sanctioned read); the asarray calls
# below only stamp host metadata ints
def save_state_shard(metric: Any, path: str, rank: int = 0, world_size: int = 1) -> str:
    """Atomically snapshot this rank's FULL state (persistence forced on).

    Writes ``path`` (``.npz`` appended when missing) via ``.tmp`` + rename:
    the file either exists complete or not at all. Returns the final path.
    """
    flat = _collect_flat(metric)
    flat["__elastic_version__"] = np.asarray(SNAPSHOT_VERSION)
    flat["__rank__"] = np.asarray(int(rank))
    flat["__world__"] = np.asarray(int(world_size))
    flat["__crc__"] = np.asarray(_payload_crc(flat), dtype=np.uint32)

    final = path if path.endswith(".npz") else path + ".npz"
    tmp = final + ".tmp"
    # file-object write: np.savez must not append its own extension to the tmp
    # name, and the fsync-before-rename is what makes the crash window clean
    with open(tmp, "wb") as fh:
        np.savez(fh, **flat)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, final)

    from torchmetrics_tpu.diag import trace as _diag

    _diag.record("snapshot.save", type(metric).__name__, path=final, rank=int(rank), world=int(world_size))
    return final


# ------------------------------------------------------------------ load/verify


# tmlint: boundary(snapshot-load) — reads a host .npz payload, never a device buffer
def _load_shard(path: str) -> Dict[str, np.ndarray]:
    try:
        with np.load(path, allow_pickle=False) as npz:
            flat = {k: np.asarray(npz[k]) for k in npz.files}
    except Exception as err:  # noqa: BLE001 — unreadable IS the corruption signal
        raise SnapshotIntegrityError(f"snapshot shard {path!r} is unreadable: {err}") from err
    for key in ("__elastic_version__", "__rank__", "__world__", "__crc__"):
        if key not in flat:
            raise SnapshotIntegrityError(
                f"snapshot shard {path!r} lacks the {key} stamp — not an elastic shard"
                " (or written by a pre-elastic layout)"
            )
    version = int(flat["__elastic_version__"])
    if version != SNAPSHOT_VERSION:
        raise SnapshotVersionError(
            f"snapshot shard {path!r} has layout version {version}, this build reads"
            f" {SNAPSHOT_VERSION} — refusing to guess at the layout"
        )
    expected = int(flat["__crc__"])
    actual = _payload_crc(flat)
    if actual != expected:
        raise SnapshotIntegrityError(
            f"snapshot shard {path!r} failed its integrity check"
            f" (crc {actual:#010x} != stamped {expected:#010x}) — the payload is corrupt"
        )
    return flat


def _resolve_shards(shards: Union[str, Sequence[str]]) -> List[str]:
    """A directory or an explicit path list -> sorted shard files.

    Leftover ``*.tmp`` files from a crashed atomic write are ignored by
    construction — only complete, renamed ``.npz`` shards participate.
    """
    if isinstance(shards, (str, os.PathLike)):
        root = os.fspath(shards)
        if os.path.isdir(root):
            found = sorted(
                os.path.join(root, name)
                for name in os.listdir(root)
                if name.endswith(".npz") and ".tmp" not in name
            )
            if not found:
                raise SnapshotIntegrityError(f"no snapshot shards found under {root!r}")
            return found
        return [root]
    return [os.fspath(p) for p in shards]


def _load_shard_set(shards: Union[str, Sequence[str]]) -> List[Dict[str, np.ndarray]]:
    loaded = [_load_shard(p) for p in _resolve_shards(shards)]
    world = {int(f["__world__"]) for f in loaded}
    if len(world) != 1:
        raise SnapshotIntegrityError(
            f"snapshot shards disagree on their saved world size ({sorted(world)})"
        )
    n = world.pop()
    ranks = sorted(int(f["__rank__"]) for f in loaded)
    if ranks != list(range(n)):
        raise SnapshotIntegrityError(
            f"incomplete snapshot shard set: saved world {n} but ranks {ranks} present"
        )
    return sorted(loaded, key=lambda f: int(f["__rank__"]))


# ------------------------------------------------------------------ reshard


def _is_metric(obj: Any) -> bool:
    return hasattr(obj, "_defaults") and hasattr(obj, "_reductions")


def _set_states(metric: Any, states: Dict[str, Any]) -> None:
    for k, v in states.items():
        object.__setattr__(metric, k, v)


def _fold_shards(metric: Any, shard_states: List[Dict[str, Any]]):
    """Fold N shards' states through a freshly planned+compiled packed fold.

    This is the live sync machinery verbatim: one :class:`PackedSyncPlan` per
    shard (same layout, validated by signature equality), the shared metadata
    table, and ``make_fold`` re-jitted for the restore-time signature — the
    "re-planned and recompiled on restore" contract, not a parallel fold
    implementation that could drift from the one production uses.
    """
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.engine import numerics as _numerics
    from torchmetrics_tpu.parallel.packing import PackedSyncPlan

    n = len(shard_states)
    original = {k: getattr(metric, k) for k in metric._defaults}
    try:
        # shard values are ANCHORED (state_dict folded their residuals in) and
        # carry NO residuals of their own, so the restore-time plan is built
        # with compensation OFF: plain sum/mean specs the reshard split
        # algebra understands — the live world re-enables its (value,
        # residual) pairing from a zero residual after the restore
        with _numerics.compensated_context(False):
            plans, metas = [], []
            for states in shard_states:
                _set_states(metric, states)
                plan = PackedSyncPlan([("", metric)], n, None)
                plans.append(plan)
                metas.append(plan.metadata_local())
        shapes = {None if m is None else m.shape for m in metas}
        if len(shapes) != 1:
            raise SnapshotReshardError(
                "snapshot shards disagree on the packed metadata layout — they were"
                " not written by the same metric definition"
            )
        world_meta = None if metas[0] is None else np.stack(metas)
        packed = []
        for plan, states in zip(plans, shard_states):
            _set_states(metric, states)
            plan.finalize(world_meta)
            packed.append(plan.pack())
        if len({p.signature() for p in plans}) != 1:
            raise SnapshotReshardError(
                "snapshot shards disagree on the packed buffer layout — mismatched"
                " state shapes or dtypes across shards"
            )
        gathered = {key: jnp.stack([p[key] for p in packed]) for key in packed[0]}
        fold = jax.jit(plans[0].make_fold())
        folded = fold(gathered).get("", {})
        return folded, plans[0]
    finally:
        _set_states(metric, original)


def _chunk_rows(n_rows: int, rank: int, world_size: int) -> Tuple[int, int]:
    """Contiguous row chunk ``[start, stop)`` for ``rank`` of ``world_size``."""
    base, rem = divmod(n_rows, world_size)
    start = rank * base + min(rank, rem)
    return start, start + base + (1 if rank < rem else 0)


def _split_count(total: int, rank: int, world_size: int) -> int:
    """Sum-preserving integer split of the aggregate update count."""
    base, rem = divmod(int(total), world_size)
    return base + (1 if rank < rem else 0)


def _reshard_metric(
    metric: Any, shard_flats: List[Dict[str, np.ndarray]], rank: int, world_size: int, prefix: str = ""
) -> None:
    import jax.numpy as jnp

    from torchmetrics_tpu.utilities.checkpoint import _from_saveable

    n = len(shard_flats)
    count_key = prefix + metric._UPDATE_COUNT_KEY
    if n == world_size:
        # same-world restore: pure per-rank identity, every state kind supported
        metric.load_state_dict(_from_saveable(dict(shard_flats[rank])), prefix=prefix)
        return

    shard_states = []
    counts = []
    for flat in shard_flats:
        restored = _from_saveable({k: v for k, v in flat.items() if k not in _META_KEYS})
        states = {}
        for attr in metric._defaults:
            key = prefix + attr
            if key not in restored:
                raise SnapshotIntegrityError(
                    f"snapshot shard lacks state {key!r} — saved by a different metric?"
                )
            states[attr] = restored[key]
        shard_states.append(states)
        # tmlint: disable=TM101 — `flat` is a loaded host .npz dict (snapshot-load)
        counts.append(int(np.asarray(flat.get(count_key, 0))))

    folded, plan = _fold_shards(metric, shard_states)
    out: Dict[str, Any] = {}
    for spec in plan.specs:
        attr = spec.attr
        if attr not in metric._defaults:  # e.g. the sentinel rider
            continue
        value = folded[attr]
        if spec.kind == "sum":
            out[attr] = value if rank == 0 else jnp.zeros_like(value)
        elif spec.kind in ("mean", "max", "min"):
            out[attr] = value  # idempotent / fixed-point folds replicate exactly
        elif spec.kind == "cat":
            if isinstance(value, list):  # empty on every shard
                out[attr] = [] if spec.was_list else value
                continue
            start, stop = _chunk_rows(int(value.shape[0]), rank, world_size)
            chunk = value[start:stop]
            out[attr] = ([chunk] if chunk.shape[0] else []) if spec.was_list else chunk
        else:
            raise SnapshotReshardError(
                f"state {attr!r} ({spec.kind} reduction) cannot be resharded from a"
                f" {n}-rank snapshot into a {world_size}-rank world: no fold algebra"
                " survives the resize. Restore into the saved world size, or rebuild"
                " the state from data."
            )
    for attr, value in out.items():
        setattr(metric, attr, value)
    metric._update_count = _split_count(sum(counts), rank, world_size)
    metric._computed = None
    if hasattr(metric, "_apply_shard_rules"):
        # the reshard algebra ran on host/single-device arrays: rule-carrying
        # states re-place onto the active state mesh so an N->M restore hands
        # back born-distributed buffers (parallel/sharding.py)
        metric._apply_shard_rules()
    if metric.__dict__.get("_comp_residuals"):
        import jax.numpy as jnp

        # shards persist ANCHORED totals (state_dict folds the residual in):
        # the restored world starts its compensation from a zero residual
        metric._comp_residuals = {
            k: jnp.zeros_like(getattr(metric, k)) for k in metric._comp_residuals
        }


def restore_resharded(
    metric: Any,
    shards: Union[str, Sequence[str]],
    rank: int = 0,
    world_size: int = 1,
    last_good: Optional[Union[str, Sequence[str]]] = None,
) -> Any:
    """Restore a saved N-rank shard set into this process as ``rank`` of ``M``.

    ``shards`` is the complete shard set of the saved world — a directory
    (leftover ``.tmp`` files from crashed writes are ignored) or explicit
    paths. With ``world_size == N`` this is an identity per-rank restore; with
    ``world_size != N`` the shards fold through a restore-time
    :class:`~torchmetrics_tpu.parallel.packing.PackedSyncPlan` (recompiled for
    the snapshot's world) and split so that an M-rank packed sync reproduces
    the N-rank result exactly (see the module docstring for the per-kind
    algebra). Works for a single ``Metric`` or a ``MetricCollection``.

    Corrupt or version-mismatched shards raise loud, typed errors on every
    rank; ``last_good`` names a previous complete shard set to fall back to
    (the fallback is recorded, never silent).
    """
    from torchmetrics_tpu.diag import trace as _diag

    if world_size < 1 or not (0 <= rank < world_size):
        raise ValueError(f"invalid target geometry: rank {rank} of world {world_size}")
    try:
        shard_flats = _load_shard_set(shards)
    except (SnapshotIntegrityError, SnapshotVersionError) as err:
        if last_good is None:
            raise
        _diag.record(
            "snapshot.fallback", type(metric).__name__,
            error=type(err).__name__, detail=str(err)[:200],
        )
        return restore_resharded(metric, last_good, rank=rank, world_size=world_size)

    if _is_metric(metric):
        _reshard_metric(metric, shard_flats, rank, world_size)
    elif getattr(metric, "_groups_checked", False) and getattr(metric, "_groups", None):
        # compute-group'd collection (incl. construction-time CSE groups,
        # engine/statespec.py): fold + split each CANONICAL owner exactly
        # once, then re-anchor the view members onto the restored owners —
        # restoring every view independently would re-run the fold N times
        # per group and (for sum states) hand every view its own rank-0 copy
        # until the next materialization overwrote it
        grouped: set = set()
        for group in metric._groups.values():
            grouped.update(group.names)
            _reshard_metric(
                metric._modules[group.owner], shard_flats, rank, world_size,
                prefix=f"{group.owner}.",
            )
        # an explicit compute_groups list may not cover every member
        for name, member in metric._modules.items():
            if name not in grouped:
                _reshard_metric(member, shard_flats, rank, world_size, prefix=f"{name}.")
        metric._state_is_copy = False
        metric._materialize_group_views()
    else:
        # ungrouped collection: every member reshards independently under its prefix
        for name, member in metric.items(keep_base=True, copy_state=False):
            _reshard_metric(member, shard_flats, rank, world_size, prefix=f"{name}.")
    _diag.record(
        "snapshot.restore", type(metric).__name__,
        saved_world=len(shard_flats), rank=int(rank), world=int(world_size),
    )
    return metric


# ------------------------------------------------------------------ continuous snapshots

#: cadence knob: ``"500"`` = snapshot every 500 updates, ``"30s"``/``"2.5s"`` =
#: every 30 / 2.5 seconds; unset = no automatic cadence (flush/signals only)
SNAPSHOT_EVERY_ENV_VAR = "TORCHMETRICS_TPU_SNAPSHOT_EVERY"

_SNAP_RE = re.compile(r"snap-(\d+)\.rank(\d+)-of-(\d+)\.npz$")


class SnapshotPolicy:
    """Snapshot cadence: every N updates and/or every T seconds (OR-combined).

    Cadence counts from the LAST snapshot: with ``every_updates=N`` the Nth
    update since the previous flush is the one that snapshots (updates 1..N-1
    do not) — the off-by-one convention the tests pin.
    """

    __slots__ = ("every_updates", "every_seconds")

    def __init__(self, every_updates: Optional[int] = None, every_seconds: Optional[float] = None) -> None:
        # None-checks, not truthiness: every_updates=0 must hit the validation
        # below (a silently-disabled cadence loses data on the next preemption)
        self.every_updates = int(every_updates) if every_updates is not None else None
        self.every_seconds = float(every_seconds) if every_seconds is not None else None
        if self.every_updates is not None and self.every_updates < 1:
            raise ValueError(f"every_updates must be >= 1 (got {every_updates})")
        if self.every_seconds is not None and self.every_seconds <= 0:
            raise ValueError(f"every_seconds must be > 0 (got {every_seconds})")

    @classmethod
    def from_env(cls) -> Optional["SnapshotPolicy"]:
        """Parse ``TORCHMETRICS_TPU_SNAPSHOT_EVERY``; None only when UNSET.

        An invalid value fails loud: silently running with no cadence is the
        exact data-loss mode the cadence exists to prevent — the operator who
        set the knob must learn about the typo before the next preemption.
        """
        raw = os.environ.get(SNAPSHOT_EVERY_ENV_VAR, "").strip().lower()
        if not raw:
            return None
        try:
            if raw.endswith("s"):
                return cls(every_seconds=float(raw[:-1]))
            return cls(every_updates=int(raw))
        except ValueError as exc:
            raise TorchMetricsUserError(
                f"invalid {SNAPSHOT_EVERY_ENV_VAR}={raw!r}: use an update count"
                " ('500') or a seconds suffix ('30s'); refusing to run with the"
                " snapshot cadence silently disabled."
            ) from exc

    def due(self, updates_since: int, seconds_since: float) -> bool:
        """Whether a snapshot is due, given progress since the last one."""
        if self.every_updates is not None and updates_since >= self.every_updates:
            return True
        if self.every_seconds is not None and seconds_since >= self.every_seconds:
            return True
        return False


def _snapshot_base(directory: str, seq: int) -> str:
    return os.path.join(directory, f"snap-{int(seq):06d}")


def list_snapshots(directory: str) -> List[Tuple[int, List[str]]]:
    """``[(seq, [shard paths])]`` for every snapshot sequence, oldest first.

    Leftover ``.tmp`` files from crashed atomic writes never match the shard
    pattern, so they are invisible here by construction.
    """
    by_seq: Dict[int, List[str]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        match = _SNAP_RE.fullmatch(name)
        if match:
            by_seq.setdefault(int(match.group(1)), []).append(os.path.join(directory, name))
    return [(seq, sorted(by_seq[seq])) for seq in sorted(by_seq)]


class ContinuousSnapshotter:
    """Cadence-driven atomic snapshots + a preemption flush for ONE metric.

    Each flush writes a new numbered sequence through :func:`save_state_shard`
    (atomic, version-stamped, CRC'd), so the directory always holds a chain of
    complete snapshots; :func:`restore_latest` walks it newest-first. ``keep``
    bounds disk: this rank's shards of older sequences are pruned after every
    successful flush (every retained sequence stays complete per rank).

    :meth:`install_signal_handlers` arms SIGTERM/SIGINT: the handler flushes a
    FINAL shard, then restores the previous handler and re-raises the signal —
    the process still dies, but the last-good chain ends at the preemption
    instant instead of the last epoch boundary. Handlers only install on the
    main thread (Python's signal contract); install once per process per
    snapshotter.
    """

    def __init__(
        self,
        metric: Any,
        directory: str,
        rank: int = 0,
        world_size: int = 1,
        policy: Optional[SnapshotPolicy] = None,
        keep: int = 2,
        clock: Callable[[], float] = _time.monotonic,
    ) -> None:
        self.metric = metric
        self.directory = os.fspath(directory)
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.policy = policy if policy is not None else SnapshotPolicy.from_env()
        self.keep = max(1, int(keep))
        self._clock = clock
        os.makedirs(self.directory, exist_ok=True)
        existing = list_snapshots(self.directory)
        self._seq = existing[-1][0] if existing else 0
        self._updates_since = 0
        self._last_flush = self._clock()
        self._prev_handlers: Dict[int, Any] = {}
        self.flushes = 0

    @property
    def seq(self) -> int:
        """Number of the last COMPLETED snapshot sequence (0 = none yet).

        Lets callers pair each flush with out-of-band bookkeeping (e.g. a
        fingerprint recorded per completed sequence): after a signal-handler
        chain runs, ``seq`` advancing past the last value observed in the hot
        loop proves the preemption flush wrote a shard rather than standing on
        the previous snapshot (mid-update skip).
        """
        return self._seq

    # ------------------------------------------------------------------ cadence

    def note_update(self) -> Optional[str]:
        """Record one metric update; snapshot when the cadence says so.

        Returns the shard path when a snapshot was written, else None.
        """
        self._updates_since += 1
        if self.policy is not None and self.policy.due(
            self._updates_since, self._clock() - self._last_flush
        ):
            return self.flush(reason="cadence")
        return None

    def flush(self, reason: str = "manual") -> str:
        """Write the next numbered snapshot sequence now (atomic per shard)."""
        seq = self._seq + 1
        path = save_state_shard(
            self.metric,
            shard_path(_snapshot_base(self.directory, seq), self.rank, self.world_size),
            rank=self.rank,
            world_size=self.world_size,
        )
        # only a written shard advances the completed-sequence watermark: a
        # failed save (disk full) must leave ``seq`` standing on the last
        # sequence that actually has a restorable shard
        self._seq = seq
        self._updates_since = 0
        self._last_flush = self._clock()
        self.flushes += 1
        from torchmetrics_tpu.diag import trace as _diag

        _diag.record(
            "snapshot.flush", type(self.metric).__name__,
            seq=self._seq, reason=reason, rank=self.rank, world=self.world_size,
        )
        self._prune()
        return path

    def _prune(self) -> None:
        """Drop THIS rank's shards beyond its newest ``keep``.

        Retention is keyed on the sequences THIS RANK has shards in, not the
        directory's global newest — ranks whose sequence counters skew (a
        manual flush on one rank, seconds-cadence jitter) must never prune
        their own newest shard just because another rank's counter ran ahead.
        """
        mine = []
        for seq, paths in list_snapshots(self.directory):
            shard = shard_path(_snapshot_base(self.directory, seq), self.rank, self.world_size)
            if shard in paths:
                mine.append((seq, shard))
        mine.sort(reverse=True)
        for _seq, stale in mine[self.keep:]:
            try:
                os.remove(stale)
            except OSError:
                pass  # already gone — pruning is best-effort

    # ------------------------------------------------------------------ preemption

    def install_signal_handlers(self, signals: Sequence[int] = (_signal.SIGTERM, _signal.SIGINT)) -> None:
        """Arm the preemption flush: on signal, write a final shard, then die.

        The previous handler is restored and the signal re-raised after the
        flush, so default termination semantics (and any outer handler) are
        preserved — this snapshotter only inserts the flush. If the re-raised
        signal turns out survivable (a caught-and-continued KeyboardInterrupt),
        the flush handler re-arms itself for the next delivery.
        """
        for signum in signals:
            self._prev_handlers[signum] = _signal.getsignal(signum)
            _signal.signal(signum, self._on_signal)

    def uninstall_signal_handlers(self) -> None:
        for signum, prev in self._prev_handlers.items():
            _signal.signal(signum, prev)
        self._prev_handlers.clear()

    def _metric_mid_mutation(self) -> bool:
        """Whether the watched metric (or any collection member) is mid-update.

        Signal handlers run between bytecodes: a flush landing between the
        update wrapper's count bump and its state writes would persist a TORN
        shard that still passes its CRC (the digest covers whatever was read).
        """
        if getattr(self.metric, "_mutation_depth", 0):
            return True
        modules = getattr(self.metric, "_modules", None)
        if modules:
            return any(getattr(m, "_mutation_depth", 0) for m in modules.values())
        return False

    def preempt_flush(self, signum: int) -> Optional[str]:
        """The signal-time flush: write a final shard, or — when the signal
        landed mid-update — stand on the last completed snapshot instead of
        persisting torn state. Returns the shard path, or None when skipped."""
        from torchmetrics_tpu.diag import trace as _diag

        if self._metric_mid_mutation():
            _diag.record(
                "snapshot.preempt", type(self.metric).__name__,
                signum=int(signum), seq=self._seq, skipped="mid-update",
            )
            return None
        path = self.flush(reason=f"signal:{signum}")
        _diag.record(
            "snapshot.preempt", type(self.metric).__name__, signum=int(signum), seq=self._seq,
        )
        return path

    def _on_signal(self, signum: int, frame: Any) -> None:
        try:
            self.preempt_flush(signum)
        finally:
            prev = self._prev_handlers.get(signum, _signal.SIG_DFL)
            _signal.signal(signum, prev if prev is not None else _signal.SIG_DFL)
            try:
                _signal.raise_signal(signum)
            finally:
                # a survivable delivery (a KeyboardInterrupt the training loop
                # catches and continues from) must leave the preemption flush
                # armed for the NEXT signal; a fatal one never reaches this
                # line. Guard: uninstall may have run inside the re-raise.
                if signum in self._prev_handlers:
                    _signal.signal(signum, self._on_signal)

    def __enter__(self) -> "ContinuousSnapshotter":
        self.install_signal_handlers()
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.uninstall_signal_handlers()


def restore_latest(
    metric: Any,
    directory: str,
    rank: int = 0,
    world_size: int = 1,
) -> int:
    """Restore the newest COMPLETE, integrity-clean snapshot sequence.

    Walks the last-good chain newest-first: a sequence that is incomplete (a
    preemption caught only some ranks mid-flush), corrupt, or
    version-mismatched is skipped with a recorded ``snapshot.fallback`` event
    and the previous one is tried — the automated form of
    ``restore_resharded(..., last_good=...)``. Returns the restored sequence
    number; raises :class:`SnapshotIntegrityError` when no sequence survives.
    """
    from torchmetrics_tpu.diag import trace as _diag

    sequences = list_snapshots(directory)
    last_err: Optional[Exception] = None
    for seq, paths in reversed(sequences):
        try:
            restore_resharded(metric, paths, rank=rank, world_size=world_size)
        except (SnapshotIntegrityError, SnapshotVersionError) as err:
            _diag.record(
                "snapshot.fallback", type(metric).__name__,
                seq=seq, error=type(err).__name__, detail=str(err)[:200],
            )
            last_err = err
            continue
        _diag.record("snapshot.restore_latest", type(metric).__name__, seq=seq, rank=int(rank))
        return seq
    if last_err is not None:
        raise SnapshotIntegrityError(
            f"no restorable snapshot sequence under {directory!r}: every candidate"
            " failed its integrity/version check"
        ) from last_err
    raise SnapshotIntegrityError(f"no snapshot sequences found under {directory!r}")
