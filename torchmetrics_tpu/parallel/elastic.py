"""Elastic resharding checkpoint/restore — packed state across world resizes.

Production pods lose and gain ranks; a checkpoint written by an N-rank world
must restore into an M-rank world without corrupting the fold semantics every
``dist_reduce_fx`` encodes. This module provides exactly that:

- :func:`save_state_shard` — one **atomic** (``.tmp`` + ``os.replace``),
  **version-stamped**, **CRC-protected** ``.npz`` snapshot of this rank's
  local states (+ update count), tagged ``(rank, world_size)``. A crash
  mid-write leaves only a ``.tmp`` file, which restore ignores — the previous
  complete snapshot stays authoritative.
- :func:`restore_resharded` — loads the *full shard set* of the saved world
  and restores it into a (possibly different) ``world_size``. The cross-shard
  fold is **re-planned and recompiled on restore** through the exact packed
  machinery the live sync uses (:class:`~torchmetrics_tpu.parallel.packing.
  PackedSyncPlan` + ``make_fold`` under ``jax.jit``), then split across the
  new world so a later M-rank packed sync reproduces the N-rank result
  bit-for-bit:

  =============  =========================================================
  ``sum``        new rank 0 carries the folded total, others zeros — the
                 M-rank sum re-produces it exactly
  ``mean``       the folded mean replicates to every rank (a mean of
                 identical values is itself) — exact for any M
  ``max/min``    the folded extremum replicates (idempotent fold) — exact
  ``cat``        concatenated rows split into contiguous chunks in rank
                 order — the M-rank concat re-produces the row order
  ``custom``/``none``  no algebra is known that survives a world resize —
                 :class:`SnapshotReshardError`, fail loud (same-world
                 restore of these states is fully supported)
  =============  =========================================================

- **Integrity is loud**: a corrupted shard (CRC mismatch, unreadable
  archive) raises :class:`SnapshotIntegrityError`; a snapshot written by a
  different layout version raises :class:`SnapshotVersionError` —
  deterministically, on every rank that attempts the restore. ``last_good``
  names a fallback shard set to restore instead (counted and recorded as a
  ``snapshot.fallback`` flight-recorder event) so a corrupted latest snapshot
  degrades to the previous one rather than to a crash loop.
"""

from __future__ import annotations

import os
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "SNAPSHOT_VERSION",
    "SnapshotIntegrityError",
    "SnapshotReshardError",
    "SnapshotVersionError",
    "restore_resharded",
    "save_state_shard",
    "shard_path",
]

#: bump when the snapshot layout changes; mismatched snapshots fail loud
SNAPSHOT_VERSION = 1

_META_KEYS = ("__elastic_version__", "__rank__", "__world__", "__crc__")


class SnapshotIntegrityError(TorchMetricsUserError):
    """The snapshot is corrupt (CRC mismatch / unreadable / incomplete set)."""


class SnapshotVersionError(TorchMetricsUserError):
    """The snapshot was written by an incompatible layout version."""


class SnapshotReshardError(TorchMetricsUserError):
    """This state layout cannot be resharded into a different world size."""


def shard_path(base: str, rank: int, world_size: int) -> str:
    """Canonical per-rank shard filename under a common ``base``."""
    return f"{base}.rank{int(rank)}-of-{int(world_size)}.npz"


def _payload_crc(flat: Dict[str, np.ndarray]) -> int:
    """Order-independent digest over every payload entry's name/dtype/shape/bytes."""
    crc = 0
    for key in sorted(flat):
        if key in _META_KEYS:
            continue
        arr = np.ascontiguousarray(flat[key])
        header = f"{key}|{arr.dtype}|{arr.shape}|".encode()
        crc = zlib.crc32(arr.tobytes(), zlib.crc32(header, crc))
    return crc & 0xFFFFFFFF


def save_state_shard(metric: Any, path: str, rank: int = 0, world_size: int = 1) -> str:
    """Atomically snapshot this rank's FULL state (persistence forced on).

    Writes ``path`` (``.npz`` appended when missing) via ``.tmp`` + rename:
    the file either exists complete or not at all. Returns the final path.
    """
    from torchmetrics_tpu.utilities.checkpoint import (
        _restore_persistence,
        _snapshot_persistence,
        _to_saveable,
    )

    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    saved_flags = _snapshot_persistence(metric)
    try:
        metric.persistent(True)
        # persisting state to disk is a DECLARED host boundary (like the sync
        # collectives): the strict transfer guard must not flag a checkpoint
        with transfer_allowed("snapshot-save"):
            flat = _to_saveable(metric.state_dict())
    finally:
        _restore_persistence(metric, saved_flags)
    flat = {k: np.asarray(v) for k, v in flat.items()}
    flat["__elastic_version__"] = np.asarray(SNAPSHOT_VERSION)
    flat["__rank__"] = np.asarray(int(rank))
    flat["__world__"] = np.asarray(int(world_size))
    flat["__crc__"] = np.asarray(_payload_crc(flat), dtype=np.uint32)

    final = path if path.endswith(".npz") else path + ".npz"
    tmp = final + ".tmp"
    # file-object write: np.savez must not append its own extension to the tmp
    # name, and the fsync-before-rename is what makes the crash window clean
    with open(tmp, "wb") as fh:
        np.savez(fh, **flat)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, final)

    from torchmetrics_tpu.diag import trace as _diag

    _diag.record("snapshot.save", type(metric).__name__, path=final, rank=int(rank), world=int(world_size))
    return final


# ------------------------------------------------------------------ load/verify


def _load_shard(path: str) -> Dict[str, np.ndarray]:
    try:
        with np.load(path, allow_pickle=False) as npz:
            flat = {k: np.asarray(npz[k]) for k in npz.files}
    except Exception as err:  # noqa: BLE001 — unreadable IS the corruption signal
        raise SnapshotIntegrityError(f"snapshot shard {path!r} is unreadable: {err}") from err
    for key in ("__elastic_version__", "__rank__", "__world__", "__crc__"):
        if key not in flat:
            raise SnapshotIntegrityError(
                f"snapshot shard {path!r} lacks the {key} stamp — not an elastic shard"
                " (or written by a pre-elastic layout)"
            )
    version = int(flat["__elastic_version__"])
    if version != SNAPSHOT_VERSION:
        raise SnapshotVersionError(
            f"snapshot shard {path!r} has layout version {version}, this build reads"
            f" {SNAPSHOT_VERSION} — refusing to guess at the layout"
        )
    expected = int(flat["__crc__"])
    actual = _payload_crc(flat)
    if actual != expected:
        raise SnapshotIntegrityError(
            f"snapshot shard {path!r} failed its integrity check"
            f" (crc {actual:#010x} != stamped {expected:#010x}) — the payload is corrupt"
        )
    return flat


def _resolve_shards(shards: Union[str, Sequence[str]]) -> List[str]:
    """A directory or an explicit path list -> sorted shard files.

    Leftover ``*.tmp`` files from a crashed atomic write are ignored by
    construction — only complete, renamed ``.npz`` shards participate.
    """
    if isinstance(shards, (str, os.PathLike)):
        root = os.fspath(shards)
        if os.path.isdir(root):
            found = sorted(
                os.path.join(root, name)
                for name in os.listdir(root)
                if name.endswith(".npz") and ".tmp" not in name
            )
            if not found:
                raise SnapshotIntegrityError(f"no snapshot shards found under {root!r}")
            return found
        return [root]
    return [os.fspath(p) for p in shards]


def _load_shard_set(shards: Union[str, Sequence[str]]) -> List[Dict[str, np.ndarray]]:
    loaded = [_load_shard(p) for p in _resolve_shards(shards)]
    world = {int(f["__world__"]) for f in loaded}
    if len(world) != 1:
        raise SnapshotIntegrityError(
            f"snapshot shards disagree on their saved world size ({sorted(world)})"
        )
    n = world.pop()
    ranks = sorted(int(f["__rank__"]) for f in loaded)
    if ranks != list(range(n)):
        raise SnapshotIntegrityError(
            f"incomplete snapshot shard set: saved world {n} but ranks {ranks} present"
        )
    return sorted(loaded, key=lambda f: int(f["__rank__"]))


# ------------------------------------------------------------------ reshard


def _is_metric(obj: Any) -> bool:
    return hasattr(obj, "_defaults") and hasattr(obj, "_reductions")


def _set_states(metric: Any, states: Dict[str, Any]) -> None:
    for k, v in states.items():
        object.__setattr__(metric, k, v)


def _fold_shards(metric: Any, shard_states: List[Dict[str, Any]]):
    """Fold N shards' states through a freshly planned+compiled packed fold.

    This is the live sync machinery verbatim: one :class:`PackedSyncPlan` per
    shard (same layout, validated by signature equality), the shared metadata
    table, and ``make_fold`` re-jitted for the restore-time signature — the
    "re-planned and recompiled on restore" contract, not a parallel fold
    implementation that could drift from the one production uses.
    """
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.parallel.packing import PackedSyncPlan

    n = len(shard_states)
    original = {k: getattr(metric, k) for k in metric._defaults}
    try:
        plans, metas = [], []
        for states in shard_states:
            _set_states(metric, states)
            plan = PackedSyncPlan([("", metric)], n, None)
            plans.append(plan)
            metas.append(plan.metadata_local())
        shapes = {None if m is None else m.shape for m in metas}
        if len(shapes) != 1:
            raise SnapshotReshardError(
                "snapshot shards disagree on the packed metadata layout — they were"
                " not written by the same metric definition"
            )
        world_meta = None if metas[0] is None else np.stack(metas)
        packed = []
        for plan, states in zip(plans, shard_states):
            _set_states(metric, states)
            plan.finalize(world_meta)
            packed.append(plan.pack())
        if len({p.signature() for p in plans}) != 1:
            raise SnapshotReshardError(
                "snapshot shards disagree on the packed buffer layout — mismatched"
                " state shapes or dtypes across shards"
            )
        gathered = {key: jnp.stack([p[key] for p in packed]) for key in packed[0]}
        fold = jax.jit(plans[0].make_fold())
        folded = fold(gathered).get("", {})
        return folded, plans[0]
    finally:
        _set_states(metric, original)


def _chunk_rows(n_rows: int, rank: int, world_size: int) -> Tuple[int, int]:
    """Contiguous row chunk ``[start, stop)`` for ``rank`` of ``world_size``."""
    base, rem = divmod(n_rows, world_size)
    start = rank * base + min(rank, rem)
    return start, start + base + (1 if rank < rem else 0)


def _split_count(total: int, rank: int, world_size: int) -> int:
    """Sum-preserving integer split of the aggregate update count."""
    base, rem = divmod(int(total), world_size)
    return base + (1 if rank < rem else 0)


def _reshard_metric(
    metric: Any, shard_flats: List[Dict[str, np.ndarray]], rank: int, world_size: int, prefix: str = ""
) -> None:
    import jax.numpy as jnp

    from torchmetrics_tpu.utilities.checkpoint import _from_saveable

    n = len(shard_flats)
    count_key = prefix + metric._UPDATE_COUNT_KEY
    if n == world_size:
        # same-world restore: pure per-rank identity, every state kind supported
        metric.load_state_dict(_from_saveable(dict(shard_flats[rank])), prefix=prefix)
        return

    shard_states = []
    counts = []
    for flat in shard_flats:
        restored = _from_saveable({k: v for k, v in flat.items() if k not in _META_KEYS})
        states = {}
        for attr in metric._defaults:
            key = prefix + attr
            if key not in restored:
                raise SnapshotIntegrityError(
                    f"snapshot shard lacks state {key!r} — saved by a different metric?"
                )
            states[attr] = restored[key]
        shard_states.append(states)
        counts.append(int(np.asarray(flat.get(count_key, 0))))

    folded, plan = _fold_shards(metric, shard_states)
    out: Dict[str, Any] = {}
    for spec in plan.specs:
        attr = spec.attr
        if attr not in metric._defaults:  # e.g. the sentinel rider
            continue
        value = folded[attr]
        if spec.kind == "sum":
            out[attr] = value if rank == 0 else jnp.zeros_like(value)
        elif spec.kind in ("mean", "max", "min"):
            out[attr] = value  # idempotent / fixed-point folds replicate exactly
        elif spec.kind == "cat":
            if isinstance(value, list):  # empty on every shard
                out[attr] = [] if spec.was_list else value
                continue
            start, stop = _chunk_rows(int(value.shape[0]), rank, world_size)
            chunk = value[start:stop]
            out[attr] = ([chunk] if chunk.shape[0] else []) if spec.was_list else chunk
        else:
            raise SnapshotReshardError(
                f"state {attr!r} ({spec.kind} reduction) cannot be resharded from a"
                f" {n}-rank snapshot into a {world_size}-rank world: no fold algebra"
                " survives the resize. Restore into the saved world size, or rebuild"
                " the state from data."
            )
    for attr, value in out.items():
        setattr(metric, attr, value)
    metric._update_count = _split_count(sum(counts), rank, world_size)
    metric._computed = None


def restore_resharded(
    metric: Any,
    shards: Union[str, Sequence[str]],
    rank: int = 0,
    world_size: int = 1,
    last_good: Optional[Union[str, Sequence[str]]] = None,
) -> Any:
    """Restore a saved N-rank shard set into this process as ``rank`` of ``M``.

    ``shards`` is the complete shard set of the saved world — a directory
    (leftover ``.tmp`` files from crashed writes are ignored) or explicit
    paths. With ``world_size == N`` this is an identity per-rank restore; with
    ``world_size != N`` the shards fold through a restore-time
    :class:`~torchmetrics_tpu.parallel.packing.PackedSyncPlan` (recompiled for
    the snapshot's world) and split so that an M-rank packed sync reproduces
    the N-rank result exactly (see the module docstring for the per-kind
    algebra). Works for a single ``Metric`` or a ``MetricCollection``.

    Corrupt or version-mismatched shards raise loud, typed errors on every
    rank; ``last_good`` names a previous complete shard set to fall back to
    (the fallback is recorded, never silent).
    """
    from torchmetrics_tpu.diag import trace as _diag

    if world_size < 1 or not (0 <= rank < world_size):
        raise ValueError(f"invalid target geometry: rank {rank} of world {world_size}")
    try:
        shard_flats = _load_shard_set(shards)
    except (SnapshotIntegrityError, SnapshotVersionError) as err:
        if last_good is None:
            raise
        _diag.record(
            "snapshot.fallback", type(metric).__name__,
            error=type(err).__name__, detail=str(err)[:200],
        )
        return restore_resharded(metric, last_good, rank=rank, world_size=world_size)

    if _is_metric(metric):
        _reshard_metric(metric, shard_flats, rank, world_size)
    else:
        # MetricCollection: every member reshards independently under its prefix
        for name, member in metric.items(keep_base=True, copy_state=False):
            _reshard_metric(member, shard_flats, rank, world_size, prefix=f"{name}.")
    _diag.record(
        "snapshot.restore", type(metric).__name__,
        saved_world=len(shard_flats), rank=int(rank), world=int(world_size),
    )
    return metric
