"""TPU-native communication backend (mesh collectives; SURVEY §5.8)."""

from torchmetrics_tpu.parallel.packing import PackedSyncPlan, PackingError
from torchmetrics_tpu.parallel.sync import (
    EvalMesh,
    axis_gather,
    axis_max,
    axis_mean,
    axis_min,
    axis_sum,
    gather_all_tensors,
    jit_distributed_available,
)

__all__ = [
    "EvalMesh",
    "PackedSyncPlan",
    "PackingError",
    "axis_gather",
    "axis_max",
    "axis_mean",
    "axis_min",
    "axis_sum",
    "gather_all_tensors",
    "jit_distributed_available",
]
