"""TPU-native communication backend (mesh collectives; SURVEY §5.8).

Fault tolerance rides the same package: ``resilience`` bounds every host
collective (deadline / retry / typed errors), ``faults`` injects deterministic
chaos at that boundary, and ``elastic`` reshards checkpointed state across
world sizes. See ``docs/pages/reliability.md``.
"""

from torchmetrics_tpu.parallel.elastic import (
    ContinuousSnapshotter,
    SnapshotIntegrityError,
    SnapshotPolicy,
    SnapshotReshardError,
    SnapshotVersionError,
    restore_latest,
    restore_resharded,
    save_state_shard,
    state_fingerprint,
)
from torchmetrics_tpu.parallel.faults import (
    CollectiveTimeout,
    CorruptPayload,
    DelayRank,
    RankDrop,
    fault_context,
)
from torchmetrics_tpu.parallel.packing import PackedSyncPlan, PackingError
from torchmetrics_tpu.parallel.resilience import (
    CollectiveTimeoutError,
    PayloadCorruptError,
    RankUnreachableError,
    SyncFaultError,
    resilience_context,
    resilience_snapshot,
)
from torchmetrics_tpu.parallel.sync import (
    EvalMesh,
    axis_gather,
    axis_max,
    axis_mean,
    axis_min,
    axis_sum,
    gather_all_tensors,
    jit_distributed_available,
)

__all__ = [
    "CollectiveTimeout",
    "CollectiveTimeoutError",
    "ContinuousSnapshotter",
    "CorruptPayload",
    "DelayRank",
    "EvalMesh",
    "PackedSyncPlan",
    "PackingError",
    "PayloadCorruptError",
    "RankDrop",
    "RankUnreachableError",
    "SnapshotIntegrityError",
    "SnapshotPolicy",
    "SnapshotReshardError",
    "SnapshotVersionError",
    "SyncFaultError",
    "axis_gather",
    "axis_max",
    "axis_mean",
    "axis_min",
    "axis_sum",
    "fault_context",
    "gather_all_tensors",
    "jit_distributed_available",
    "resilience_context",
    "resilience_snapshot",
    "restore_latest",
    "restore_resharded",
    "save_state_shard",
    "state_fingerprint",
]
