"""TPU-native communication backend (mesh collectives; SURVEY §5.8).

Fault tolerance rides the same package: ``resilience`` bounds every host
collective (deadline / retry / typed errors), ``faults`` injects deterministic
chaos at that boundary, and ``elastic`` reshards checkpointed state across
world sizes. See ``docs/pages/reliability.md``.
"""

from torchmetrics_tpu.parallel.elastic import (
    ContinuousSnapshotter,
    SnapshotIntegrityError,
    SnapshotPolicy,
    SnapshotReshardError,
    SnapshotVersionError,
    restore_latest,
    restore_resharded,
    save_state_shard,
    state_fingerprint,
)
from torchmetrics_tpu.parallel.faults import (
    CollectiveTimeout,
    CorruptPayload,
    DelayRank,
    RankDrop,
    fault_context,
)
from torchmetrics_tpu.parallel.packing import PackedSyncPlan, PackingError
from torchmetrics_tpu.parallel.resilience import (
    CollectiveTimeoutError,
    PayloadCorruptError,
    RankUnreachableError,
    SyncFaultError,
    resilience_context,
    resilience_snapshot,
)
from torchmetrics_tpu.parallel.sync import (
    EvalMesh,
    axis_gather,
    axis_max,
    axis_mean,
    axis_min,
    axis_sum,
    gather_all_tensors,
    jit_distributed_available,
)

# SPMD sharded-state engine (parallel/sharding.py): exported LAZILY (PEP 562)
# — sharding sits above the engine package in the import graph (it consumes
# EngineStats + the statespec registry), while engine/epoch.py imports THIS
# package's packing/resilience at module level; an eager import here would be
# a cycle. `from torchmetrics_tpu.parallel import mesh_context` still works.
_SHARDING_EXPORTS = (
    "axis_size",
    "build_mesh",
    "data_axis_size",
    "ensure_multihost",
    "is_sharded",
    "mesh_context",
    "metric_mesh",
    "partition_rules_context",
    "reshard_states",
    "set_mesh",
    "set_partition_rules",
    "shard_batch",
    "sharding_enabled",
)


def __getattr__(name: str):
    if name in _SHARDING_EXPORTS or name == "sharding":
        import importlib

        # importlib, not `from ... import`: a from-import resolves through
        # THIS __getattr__ while the submodule is still initializing — recursion
        sharding = importlib.import_module("torchmetrics_tpu.parallel.sharding")
        return sharding if name == "sharding" else getattr(sharding, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "CollectiveTimeout",
    "CollectiveTimeoutError",
    "ContinuousSnapshotter",
    "CorruptPayload",
    "DelayRank",
    "EvalMesh",
    "PackedSyncPlan",
    "PackingError",
    "PayloadCorruptError",
    "RankDrop",
    "RankUnreachableError",
    "SnapshotIntegrityError",
    "SnapshotPolicy",
    "SnapshotReshardError",
    "SnapshotVersionError",
    "SyncFaultError",
    "axis_gather",
    "axis_max",
    "axis_mean",
    "axis_min",
    "axis_size",
    "axis_sum",
    "build_mesh",
    "data_axis_size",
    "ensure_multihost",
    "fault_context",
    "is_sharded",
    "mesh_context",
    "metric_mesh",
    "partition_rules_context",
    "reshard_states",
    "set_mesh",
    "set_partition_rules",
    "shard_batch",
    "sharding_enabled",
    "gather_all_tensors",
    "jit_distributed_available",
    "resilience_context",
    "resilience_snapshot",
    "restore_latest",
    "restore_resharded",
    "save_state_shard",
    "state_fingerprint",
]
