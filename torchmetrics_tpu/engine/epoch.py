"""Fused epoch engine — packed sync and cached sync→compute executables.

PR 1's update engine removed the per-step dispatch floor; the epoch boundary
was still eager: one host collective per state tensor (and per list element)
behind per-state metadata gathers, and a full Python re-trace of ``compute()``
every epoch end. This module bounds the epoch boundary the same way the update
engine bounded the step:

- :class:`EpochEngine` (one per :class:`~torchmetrics_tpu.metric.Metric`):

  * **Packed sync** — all of a metric's states ride one
    :class:`~torchmetrics_tpu.parallel.packing.PackedSyncPlan`: at most one
    metadata gather + one collective per (role, dtype) buffer, with the unpack
    and every state's ``dist_reduce_fx`` fold compiled into ONE cached
    executable keyed by the plan signature.
  * **Cached compute** — ``compute()`` traces once per state signature into a
    ``jax.jit`` executable (:func:`traced_compute` swaps traced states onto
    the metric exactly like the update engine's ``traced_update``); repeated
    epoch ends are a single cached dispatch, zero re-traces.
  * **Fused sync→reduce-fold→compute** — when both are compilable, the fold
    and the compute body lower into the SAME graph: epoch end is one metadata
    gather + O(dtypes) collectives + one dispatch returning both the synced
    states and the final value.

- :class:`CollectionEpoch` (one per ``MetricCollection``): a single plan spans
  every compute-group owner, so an N-metric collection syncs in O(dtypes)
  collectives total instead of per-metric × per-state.

Anything that cannot ride the packed/cached path — custom ``dist_sync_fn``,
``compute_on_cpu``, host-object list states, untraceable computes — falls back
to the eager path with the reason counted in :class:`EngineStats`
(``fallback_reasons``), never silently.
"""

from __future__ import annotations

from time import perf_counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu.diag import costs as _costs
from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import lineage as _lineage
from torchmetrics_tpu.diag import profile as _profile
from torchmetrics_tpu.diag import sentinel as _sentinel
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.diag.transfer_guard import transfer_allowed
from torchmetrics_tpu.engine.compiled import (
    _FALLBACK,
    _Ineligible,
    _container_changed,
    _is_jax_array,
    annotation_scope,
    completion_probe,
    holds_nested_metrics,
)
from torchmetrics_tpu.engine import persist as _persist
from torchmetrics_tpu.engine import txn as _txn
from torchmetrics_tpu.engine.stats import EngineStats
from torchmetrics_tpu.parallel import packing as _packing
from torchmetrics_tpu.parallel import resilience as _resilience
from torchmetrics_tpu.parallel.packing import PackedSyncPlan, PackingError, all_gather_backbone

#: sentinel: the packed sync succeeded but the compute half must run outside
#: the fused graph (untraceable compute) — callers compute eagerly on the
#: freshly synced states.
NO_VALUE = object()


def _note_async_sync(stats: EngineStats) -> None:
    """Stamp a completed packed sync for async-overlap attribution.

    The synced states written back above are device FUTURES — with async
    dispatch on, the caller's next-epoch enqueues proceed while the sync's
    fold work completes; the window until the next join is credited as
    ``async.sync.overlap`` (see ``engine/async_dispatch.note_epoch_sync``).
    No-op when async mode is off.
    """
    from torchmetrics_tpu.engine.async_dispatch import note_epoch_sync

    note_epoch_sync(stats)


def _note_plan_coverage(stats: EngineStats, plan: "PackedSyncPlan") -> None:
    """Attest a packed sync's membership when it did NOT cover the full world.

    Complete full-world folds stay silent (nothing to attest); a degraded
    re-plan or a process-group subset stamps who contributed and who was
    excluded, so later observations of the synced value carry the membership.
    """
    if plan.degraded or len(plan.members) != plan.world_size:
        _lineage.note_coverage(
            stats.owner,
            plan.members,
            excluded=[(r, "sync-fault") for r in plan.excluded_ranks],
        )


def traced_compute(metric: Any, state: Dict[str, Any]) -> Any:
    """Run ``metric``'s original compute body as ``state -> value`` (trace-safe).

    Mirrors ``traced_update``: the metric's ``__dict__`` is snapshotted and
    restored wholesale so a trace can never leak tracers onto the live object,
    and a compute with side effects a cached executable would lose — rebinding
    a state or non-state attribute, mutating a host container in place —
    aborts compilation via :class:`_Ineligible` instead of silently diverging.
    """
    names = tuple(metric._defaults)
    snapshot = dict(metric.__dict__)
    containers = {
        k: (list(v) if isinstance(v, list) else dict(v) if isinstance(v, dict) else set(v))
        for k, v in snapshot.items()
        if k not in names and isinstance(v, (list, dict, set))
    }
    try:
        for k in names:
            object.__setattr__(metric, k, state[k])
        value = metric._raw_compute()
        for k, v in metric.__dict__.items():
            if k in names:
                if v is not state[k]:
                    raise _Ineligible(f"compute rebinds state {k!r}")
                continue
            if snapshot.get(k, _FALLBACK) is not v:
                raise _Ineligible(f"compute writes non-state attribute {k!r}")
            if k in containers and _container_changed(v, containers[k]):
                raise _Ineligible(f"compute mutates non-state container {k!r} in place")
        return value
    finally:
        metric.__dict__.clear()
        metric.__dict__.update(snapshot)
        for k, saved in containers.items():
            live = snapshot[k]
            if _container_changed(live, saved):
                if isinstance(live, list):
                    live[:] = saved
                else:
                    live.clear()
                    live.update(saved)


def _state_signature(state: Dict[str, Any]) -> Optional[Tuple]:
    """Shape/dtype cache key over a (possibly list-valued) state dict."""
    sig: List[Any] = []
    for k, v in state.items():
        if _is_jax_array(v):
            sig.append((k, tuple(v.shape), str(v.dtype)))
        elif isinstance(v, list):
            if not all(_is_jax_array(x) for x in v):
                return None
            sig.append((k, "list", tuple((tuple(x.shape), str(x.dtype)) for x in v)))
        else:
            return None
    return tuple(sig)


def _collect_state(metric: Any) -> Optional[Dict[str, Any]]:
    state: Dict[str, Any] = {}
    for k in metric._defaults:
        v = getattr(metric, k)
        if _is_jax_array(v):
            state[k] = v
        elif isinstance(v, list) and all(_is_jax_array(x) for x in v):
            state[k] = list(v)
        else:
            return None
    return state


def _plan_fingerprint(plan: PackedSyncPlan, mode: str = "host") -> Dict[str, Any]:
    """Signature digest of a packed plan for retrace-cause attribution.

    A fold/fused executable recompiling after warmup is attributed to the
    nearest-changed aspect: the spec layout (``treedef-change``), a state dtype
    (``dtype-change``), per-rank shapes/raggedness (``shape-change``), or the
    world geometry / buffer layout / exchange mode (``plan-change`` — the
    in-graph data-axis view and the host-gathered view carry different input
    shardings, so a mode flip IS a plan-level recompile, attributed, never
    "unknown").
    """
    return {
        "treedef": tuple((s.owner, s.attr, s.kind, s.was_list) for s in plan.specs),
        "dtype": tuple(s.dtype for s in plan.specs),
        "shape": tuple((s.shape, s.elem_shapes, s.world_dim0) for s in plan.specs),
        "plan": (mode, plan.world_size, plan.members, tuple(sorted(plan._group_sizes.items()))),
    }


def _compute_fingerprint(sig: Tuple, device: str) -> Dict[str, Any]:
    """Signature digest of a compute-state signature (see ``_state_signature``).

    List lengths live in the ``shape`` aspect: a list state growing between
    epochs is a shape change of the same pytree slot, not a new treedef.
    """
    names: List[Any] = []
    dtypes: List[Any] = []
    shapes: List[Any] = []
    for entry in sig:
        if entry[1] == "list":
            names.append((entry[0], "list"))
            dtypes.append(tuple(d for _, d in entry[2]))
            shapes.append(tuple(s for s, _ in entry[2]))
        else:
            names.append((entry[0], "array"))
            shapes.append(entry[1])
            dtypes.append(entry[2])
    return {"treedef": tuple(names), "dtype": tuple(dtypes), "shape": tuple(shapes), "device": device}


def _world_size() -> int:
    import jax

    try:
        return jax.process_count()
    except Exception:  # noqa: BLE001 — un-initialized backend reads as world 1
        return 1


def _degraded_replan(
    plan: PackedSyncPlan, stats: EngineStats, exc: "_resilience.SyncFaultError"
) -> PackedSyncPlan:
    """Re-plan over the surviving membership after a classified sync fault.

    The culprit comes from the fault itself when it names a rank (rank-drop,
    a delayed rank past the deadline) or from the PR-5 straggler detector's
    last attribution otherwise. No culprit, degraded mode disallowed, or no
    survivors left => the typed error propagates (fail loud beats fold wrong).
    The re-plan is membership-keyed: ``plan.signature()`` includes ``members``,
    so the degraded fold compiles (and caches) separately from the full-world
    one, and the ``degraded`` marker + ``sync.degraded`` event + counter keep
    the partial result observable at every surface.
    """
    policy = _resilience.current_policy()
    # fresh evidence only: the fault names its culprit, or the MOST RECENT
    # flagged straggler does (consume-once — a stale attribution must not
    # silently exclude a healthy rank's data epochs later)
    culprit = exc.rank if exc.rank is not None else _resilience.consume_straggler_hint()
    if not policy.degraded or culprit is None or culprit not in plan.members or len(plan.members) < 2:
        raise exc
    survivors = tuple(m for m in plan.members if m != culprit)
    _diag.record(
        "sync.degraded", stats.owner,
        rank=int(culprit), error=type(exc).__name__, label=exc.label,
        survivors=survivors, attempts=exc.attempts,
    )
    replanned = PackedSyncPlan(plan._metrics, plan.world_size, survivors)
    replanned.degraded = True
    replanned.excluded_ranks = plan.excluded_ranks + (int(culprit),)
    return replanned


def _exchange(
    plan: PackedSyncPlan, stats: EngineStats
) -> Tuple[Dict[str, Any], PackedSyncPlan, str]:
    """Run the (fault-bounded) exchange; returns ``(gathered, live plan, mode)``.

    The live plan is the one the caller must fold/cache against: a classified
    collective fault (timeout past the deadline, unreachable rank — typed
    errors from ``parallel/resilience.py``, never an indefinite hang) degrades
    the sync onto a re-planned surviving membership when policy allows, so the
    returned plan may exclude the culprit rank. Retries spent inside the
    bounded collectives are folded into ``stats.sync_retries``.

    ``mode`` names how the buffers were exchanged — ``"local"`` (world 1),
    ``"host"`` (packed host gather), ``"emulated"``/``"spmd"`` (the in-graph
    data-axis view, :func:`~torchmetrics_tpu.parallel.packing.mesh_world_view`)
    or ``"noop"`` (nothing to exchange) — and keys the fold caches, since the
    gathered views carry mode-specific input shardings.
    """
    retries_before = _resilience.total_retries()
    try:
        while True:
            try:
                gathered, mode = _exchange_once(plan, stats)
                if plan.degraded:
                    # counted on COMPLETION, not on the replan decision — a
                    # degrade that itself fails must not read as a degraded fold
                    stats.sync_degraded_folds += 1
                skipped = getattr(plan, "skipped_sharded", ())
                if skipped:
                    # live-sharded states never entered the host exchange:
                    # their cross-device sync is the in-graph psum/psum_scatter
                    # the SPMD executable already lowered (parallel/sharding.py)
                    stats.gather_skipped += len(skipped)
                    stats.psum_syncs += sum(
                        1 for _, _, fold, _ in skipped if fold in ("sum", "mean")
                    )
                    _diag.record(
                        "sync.shard_skip", stats.owner,
                        states=len(skipped),
                        attrs=tuple(f"{o}:{a}" if o else a for o, a, _, _ in skipped),
                    )
                    if (
                        plan.world_size > 1
                        and mode not in ("emulated", "spmd")
                        and any(not spans for _, _, _, spans in skipped)
                    ):
                        # multi-host honesty: a process-LOCAL mesh only folded
                        # this process's contributions — skipping the gather is
                        # exact only when the mesh spans every process. Loud,
                        # once (the warnings module dedups this call site):
                        # partial totals must never be silent.
                        from torchmetrics_tpu.utilities.prints import rank_zero_warn

                        rank_zero_warn(
                            "Sharded metric state on a process-local mesh skipped a"
                            f" {plan.world_size}-process sync: the in-graph collectives"
                            " folded only THIS process's contributions. Build the state"
                            " mesh over the global device set (all processes) for"
                            " multi-host sharding, or leave sharding off and ride the"
                            " packed gather.",
                            UserWarning,
                        )
                return gathered, plan, mode
            except _resilience.SyncFaultError as exc:
                # each pass excludes exactly one culprit; bounded by world size
                plan = _degraded_replan(plan, stats, exc)
    finally:
        stats.sync_retries += _resilience.total_retries() - retries_before


def _exchange_once(
    plan: PackedSyncPlan, stats: EngineStats
) -> Tuple[Dict[str, Any], str]:
    """Run the metadata exchange + buffer collectives for ``plan``.

    Returns ``(gathered, mode)``. One-process worlds skip the collectives
    entirely (the gathered view is the local buffer with a world axis of 1) —
    packed sync then costs ZERO host transfers, which is exactly the
    single-chip epoch cost the north star asks for.

    With a live 2-D mesh whose data axis matches the world size
    (:func:`~torchmetrics_tpu.parallel.packing.ingraph_sync_mode`), the packed
    buffers are exchanged as data-axis-sharded world VIEWS instead of host
    gathers: the fold's stacked reduction over dim 0 then lowers to an
    in-graph psum/pmax/pmin (all_gather for cat states) inside the same
    compiled executable. The host ``bounded_collective`` remains only for the
    metadata control probe on real multi-host pods (``"spmd"``) and for the
    eager ``"host"`` fallback. Metadata validation errors propagate (fail loud
    on every rank).
    """
    from torchmetrics_tpu.parallel import sharding as _sharding

    rec = _diag.active_recorder()
    measuring = rec is not None or _profile.active_profile() is not None
    t0 = perf_counter() if measuring else 0.0
    if plan.world_size == 1:
        mode = "local"
    else:
        mode = (
            _packing.ingraph_sync_mode(plan, _sharding.metric_mesh(), _sharding.data_axis_size())
            or "host"
        )
    if not plan.specs and not plan.timeline:
        # every state is live-sharded (its sync is already in-graph) or the
        # plan is genuinely empty: the packed buffers would be zero-row and
        # the metadata gather pure control noise — skip the exchange wholesale
        plan.finalize(None)
        stats.sync_noop_plans += 1
        _diag.record(
            "sync.noop", stats.owner,
            world=plan.world_size, mode=mode,
            sharded=len(getattr(plan, "skipped_sharded", ())),
        )
        return {}, mode
    meta = plan.metadata_local()
    had_meta = False
    ingraph = mode in ("emulated", "spmd")
    if meta is None:
        plan.finalize(None)
    elif plan.world_size == 1:
        plan.finalize(meta[None, :])
    elif mode == "emulated":
        # one real process emulating the world: every rank computes
        # byte-identical metadata, so tiling locally IS the gathered view —
        # zero host collectives, same rows the host gather would return
        plan.finalize(np.repeat(meta[None, :], plan.world_size, axis=0))
    else:
        had_meta = True
        # sanctioned boundary: the metadata probe is host data by design — every
        # rank must inspect the world layout before entering the buffer collectives
        with transfer_allowed("sync-metadata"):
            gathered_meta = np.asarray(all_gather_backbone(meta, label="meta", members=plan.members))
        stats.sync_metadata_gathers += 1
        plan.finalize(gathered_meta)
    local = plan.pack()
    gathered: Dict[str, Any] = {}
    bytes_moved = 0
    ingraph_bufs = 0
    for key in sorted(local):  # deterministic collective order on every rank
        buf = local[key]
        if plan.world_size == 1:
            gathered[key] = buf[None]
            continue
        if ingraph:
            # data-axis world view: no host collective, no transfer — the
            # cross-rank reduction compiles into the consuming fold/fused
            # executable (psum for reduce buffers, all_gather for gathers)
            gathered[key] = _packing.mesh_world_view(
                buf, plan.world_size, _sharding.metric_mesh(),
                multiprocess=(mode == "spmd"), label=key,
            )
            ingraph_bufs += 1
            if key.startswith("reduce:"):
                stats.psum_syncs += 1
            continue
        gathered[key] = all_gather_backbone(buf, label=key, members=plan.members)
        stats.sync_collectives += 1
        bytes_moved += int(getattr(buf, "nbytes", 0)) * plan.world_size
    stats.sync_bytes_moved += bytes_moved
    if ingraph_bufs:
        stats.ingraph_syncs += 1
        _diag.record(
            "sync.ingraph", stats.owner,
            world=plan.world_size, buffers=ingraph_bufs, mode=mode,
        )
    # divergence audit (opt-in): the metadata exchange carried per-state value
    # fingerprints; surface what the cross-rank comparison found
    for finding in getattr(plan, "audit_results", ()):
        if finding.get("flag"):
            if finding["flag"] == "rank-invariant-divergence":
                stats.sync_divergence_flags += 1
            _diag.record(
                "sync.audit", finding["owner"] or stats.owner,
                attr=finding["attr"], flag=finding["flag"], divergent=finding["divergent"],
            )
    # cross-rank timeline (diag/timeline.py, piggybacked on the metadata
    # gather): offset-corrected barrier arrivals attribute the straggler rank;
    # a skew past the threshold is a counted, recorded fact
    timeline = getattr(plan, "timeline_result", None)
    if timeline is not None:
        skew = timeline["skew_us"]
        if timeline["calibrated"] and skew > _profile.straggler_threshold_us():
            stats.sync_straggler_flags += 1
            # remember the attribution: a later collective timeout with no
            # culprit of its own degrades onto this rank's exclusion
            _resilience.note_straggler(timeline["last_rank"])
            _diag.record(
                "sync.straggler", stats.owner,
                rank=timeline["last_rank"], skew_us=skew,
                corrected_us=tuple(timeline["corrected_us"]),
                offsets_us=tuple(timeline["offsets_us"]),
            )
    if _profile.active_profile() is not None:
        # barrier-exit anchor: the NEXT sync's gathered prev_post stamps
        # estimate per-rank clock offsets from this collective's exit
        _profile.note_sync_exit()
    sync_us = round((perf_counter() - t0) * 1e6, 3) if measuring else 0.0
    if measuring:
        _hist.observe(stats.owner, "sync", "sync_us", sync_us)
        _hist.observe(stats.owner, "sync", "sync_bytes", bytes_moved)
    if rec is not None:
        rec.record(
            "sync.exchange", stats.owner,
            dispatch_us=sync_us,
            world=plan.world_size, buffers=len(local), metadata=had_meta, bytes=bytes_moved,
            mode=mode,
        )
    return gathered, mode


def _write_synced(metric: Any, states: Dict[str, Any], plan: PackedSyncPlan, owner: str) -> None:
    from torchmetrics_tpu.engine import numerics as _numerics

    for attr, val in states.items():
        if attr.startswith(_numerics.SYNC_RES_PREFIX):
            # the two-sum fold's post-anchor residual for a compensated state
            _numerics.set_residual(metric, attr[len(_numerics.SYNC_RES_PREFIX):], val)
        else:
            setattr(metric, attr, val)
    for attr in plan.none_folded_attrs(owner):
        metric._none_folded.add(attr)


def _run_fold(
    plan: PackedSyncPlan,
    gathered: Dict[str, Any],
    cache: Dict[Tuple, Any],
    stats: EngineStats,
    fingerprints: List[Dict[str, Any]],
    mode: str = "host",
) -> Optional[Dict[str, Dict[str, Any]]]:
    """Dispatch the plan's fold through the signature-keyed executable cache.

    Returns the folded ``{owner: {attr: value}}`` dict, or None when the fold
    cannot trace (counted; a CACHED executable failing re-raises — that is a
    real bug, not an eligibility miss). Shared by the per-metric and the
    collection engines so the fallback/counter semantics cannot drift apart.
    ``fingerprints`` is the caller-owned list of previously compiled plan
    fingerprints — a fold compile past the first is attributed and recorded as
    a ``sync.fold_retrace`` with its cause. ``mode`` is the exchange mode from
    :func:`_exchange` and keys the cache: the in-graph data-axis views and the
    host-gathered replicated views carry different input shardings, so an AOT
    executable compiled for one must never be dispatched on the other.
    """
    if not plan.specs:
        # no-op plan (every state live-sharded): nothing to unpack or fold —
        # compiling a trivial executable for an empty pytree is pure waste
        return {}
    sig = (mode, plan.signature())
    entry = cache.get(sig)
    first = entry is None
    try:
        import jax

        if first:
            entry = (
                _costs.aot_compile(
                    jax.jit(plan.make_fold()), owner=stats.owner, kind="sync-fold",
                    args=(gathered,), stats=stats,
                ),
                annotation_scope(stats.owner, "sync-fold", sig),
            )
        fn, scope = entry
        with jax.profiler.TraceAnnotation(scope):
            folded = fn(gathered)
    except Exception as exc:  # noqa: BLE001 — an untraceable custom fold demotes
        if not first:
            raise
        stats.fallback(f"sync:fold-trace-failed:{type(exc).__name__}")
        return None
    if first:
        cache[sig] = entry
        stats.sync_fold_traces += 1
        fp = _plan_fingerprint(plan, mode)
        cause = _diag.attribute_retrace(fp, fingerprints)
        fingerprints.append(fp)
        if cause != "initial":
            stats.retrace_causes[cause] += 1
        _diag.record(
            "sync.fold_trace" if cause == "initial" else "sync.fold_retrace",
            stats.owner, cause=cause,
        )
    return folded


class EpochEngine:
    """Packed-sync + cached-compute cache for ONE metric instance.

    Created lazily by :meth:`Metric._epoch_engine`; excluded from
    pickling/cloning (executables are rebuilt per process/instance).
    """

    def __init__(self, metric: Any) -> None:
        self._metric = metric
        self.stats = EngineStats("epoch:" + type(metric).__name__)
        self._fold_cache: Dict[Tuple, Any] = {}
        self._fused_cache: Dict[Tuple, Any] = {}
        self._compute_cache: Dict[Tuple, Any] = {}
        # compiled-signature fingerprints per cache, for retrace-cause attribution
        self._fold_fps: List[Dict[str, Any]] = []
        self._fused_fps: List[Dict[str, Any]] = []
        self._compute_fps: List[Dict[str, Any]] = []
        self._transient_fails: Dict[Tuple, int] = {}  # key -> classified-failure count (ladder budget)
        self._compute_ok = not holds_nested_metrics(metric) and "_raw_compute" in metric.__dict__

    # ------------------------------------------------------------------ sync

    def _plan(self, process_group: Optional[Sequence[int]]) -> Optional[PackedSyncPlan]:
        try:
            return PackedSyncPlan([("", self._metric)], _world_size(), process_group)
        except PackingError as exc:
            self.stats.fallback(f"sync:{exc}")
            return None

    def packed_sync(self, process_group: Optional[Sequence[int]] = None) -> bool:
        """Fold-only packed sync; writes synced states onto the metric.

        Returns True when handled; False requests the eager per-tensor path.
        """
        plan = self._plan(process_group)
        if plan is None:
            return False
        gathered, plan, mode = _exchange(plan, self.stats)
        folded = _run_fold(plan, gathered, self._fold_cache, self.stats, self._fold_fps, mode)
        if folded is None:
            return False
        _write_synced(self._metric, folded.get("", {}), plan, "")
        self.stats.packed_syncs += 1
        _note_async_sync(self.stats)
        _note_plan_coverage(self.stats, plan)
        return True

    def sync_and_compute(self, process_group: Optional[Sequence[int]] = None):
        """The fused chain: packed exchange → one executable doing
        unpack + reduce-fold + compute in a single graph.

        Returns ``None`` when nothing was done (caller goes fully eager), or
        ``(value,)`` after writing the synced states; ``value`` is
        :data:`NO_VALUE` when the compute half must run eagerly on the synced
        states (the sync half still rode the packed path).
        """
        m = self._metric
        plan = self._plan(process_group)
        if plan is None:
            return None
        gathered, plan, mode = _exchange(plan, self.stats)
        # sharded states live OUTSIDE the exchange (their cross-device sync is
        # in-graph): they join the fused graph as a SECOND argument, so the
        # packed-buffer fold, the sharded leaves' SPMD reduction, and the
        # compute body all lower into ONE executable — the old sharded-skip
        # special case collapses into the same GSPMD program
        skipped = tuple(getattr(plan, "skipped_sharded", ()))
        live = {attr: getattr(m, attr) for owner, attr, _, _ in skipped if not owner}
        live_sig = _state_signature(live) if live else ()
        live_token = self._device_token(live) if live else ""
        sig = ("fused", mode, plan.signature(), live_sig, live_token)
        entry = self._fused_cache.get(sig)
        if entry is _FALLBACK or not self._compute_ok or (live and live_sig is None):
            return self._fold_then_no_value(plan, gathered, mode)
        first = entry is None
        rec = _diag.active_recorder()
        profiling = _profile.active_profile() is not None
        measuring = rec is not None or profiling
        t_dispatch = perf_counter() if measuring else 0.0
        try:
            import jax

            if first:
                fold = plan.make_fold()
                owner = self.stats.owner

                def fused(bufs, live_states):
                    states = fold(bufs).get("", {})
                    full = {**live_states, **states}
                    with jax.named_scope(f"{owner}:compute"):
                        value = traced_compute(m, full)
                    if _sentinel.ATTR in states:
                        # the final value's health folds into the same graph:
                        # a NaN/Inf compute output raises the (already
                        # cross-rank-ORed) sentinel without any host read
                        states = dict(states)
                        states[_sentinel.ATTR] = _sentinel.value_flags(states[_sentinel.ATTR], value, m)
                    return states, value

                entry = (
                    _costs.aot_compile(
                        jax.jit(fused), owner=owner, kind="sync-compute", args=(gathered, live),
                        stats=self.stats,
                    ),
                    annotation_scope(owner, "sync-compute", sig),
                )
            fn, scope = entry
            if measuring:
                t_dispatch = perf_counter()
            with jax.profiler.TraceAnnotation(scope):
                states, value = fn(gathered, live)
        except Exception as exc:  # noqa: BLE001 — untraceable compute: sync still packed
            if not first:
                raise
            classified = _txn.classify_and_demote(
                self._fused_cache, _FALLBACK, self._transient_fails, sig, exc
            )
            if isinstance(exc, _Ineligible):
                reason = str(exc)
            elif classified is not None:
                reason = f"fused-dispatch-{classified}"
            else:
                reason = f"fused-trace-failed:{type(exc).__name__}"
            self.stats.fallback(reason)
            return self._fold_then_no_value(plan, gathered, mode)
        if first:
            self._fused_cache[sig] = entry
            self.stats.compute_traces += 1
            self.stats.sync_fold_traces += 1
            _persist.record_compile(self.stats.owner, "sync-compute")
            fp = _plan_fingerprint(plan, mode)
            if live:
                # the live sharded leaves are fused-graph inputs too: their
                # layout/placement changing is an attributable retrace cause
                fp["treedef"] = (fp["treedef"], tuple(e[0] for e in live_sig))
                fp["shape"] = (fp["shape"], tuple(e[1] for e in live_sig))
                fp["dtype"] = (fp["dtype"], tuple(e[2] for e in live_sig))
                fp["plan"] = (fp["plan"], live_token)
            cause = _diag.attribute_retrace(fp, self._fused_fps)
            self._fused_fps.append(fp)
            if cause != "initial":
                self.stats.retrace_causes[cause] += 1
            if rec is not None:
                rec.record(
                    "compute.trace" if cause == "initial" else "compute.retrace",
                    self.stats.owner, cause=cause, fused=True,
                )
        else:
            self.stats.compute_cache_hits += 1
        self.stats.compute_dispatches += 1
        self.stats.packed_syncs += 1
        dispatch_us = round((perf_counter() - t_dispatch) * 1e6, 3) if measuring else 0.0
        if measuring:
            # both families: a compute dispatch IS a dispatch (kind label keeps
            # it separable) AND feeds the compute-specific latency series
            _hist.observe(self.stats.owner, "compute", "dispatch_us", dispatch_us)
            _hist.observe(self.stats.owner, "compute", "compute_us", dispatch_us)
        device_us = None
        if profiling and not first:
            device_us = completion_probe(value, self.stats.owner, "compute", self.stats, t_dispatch)
        _note_plan_coverage(self.stats, plan)
        # the fused sync→compute result is an OBSERVATION: stamp what it
        # covers (watermarks + any degraded membership) before it returns
        partial = plan.degraded or len(plan.members) != plan.world_size
        prov = _lineage.observe_metric(m, "compute", coverage=plan.coverage() if partial else None)
        if rec is not None:
            span = {} if prov is None or prov.span is None else {"lineage": prov.span}
            rec.record(
                "compute.dispatch", self.stats.owner,
                dispatch_us=dispatch_us, fused=True, cached=not first, **span,
            )
            if device_us is not None:
                rec.record("compute.probe", self.stats.owner, dispatch_us=dispatch_us, device_us=device_us)
        _write_synced(m, states, plan, "")
        _note_async_sync(self.stats)
        return (value,)

    def _fold_then_no_value(self, plan: PackedSyncPlan, gathered: Dict[str, Any], mode: str = "host"):
        """Fold-only completion for an exchange whose compute half can't fuse."""
        folded = _run_fold(plan, gathered, self._fold_cache, self.stats, self._fold_fps, mode)
        if folded is None:
            return None
        _write_synced(self._metric, folded.get("", {}), plan, "")
        self.stats.packed_syncs += 1
        _note_async_sync(self.stats)
        _note_plan_coverage(self.stats, plan)
        return (NO_VALUE,)

    # ------------------------------------------------------------------ compute

    def cached_compute(self) -> Tuple[bool, Any]:
        """Dispatch ``compute()`` through a cached executable.

        Returns ``(True, value)`` when handled; ``(False, None)`` requests the
        eager compute (reason counted).
        """
        m = self._metric
        if not self._compute_ok:
            self.stats.fallback("compute:nested-metric")
            return False, None
        if m.compute_on_cpu:
            self.stats.fallback("compute:compute-on-cpu")
            return False, None
        state = _collect_state(m)
        sig = _state_signature(state) if state is not None else None
        if sig is None:
            self.stats.fallback("compute:non-array-state")
            return False, None
        sentinel_in = getattr(m, _sentinel.ATTR, None) if _sentinel.sentinel_enabled() else None
        has_sentinel = sentinel_in is not None
        key = (sig, self._device_token(state), has_sentinel)
        entry = self._compute_cache.get(key)
        if entry is _FALLBACK:
            self.stats.fallback("compute:uncompilable-signature")
            return False, None
        first = entry is None
        rec = _diag.active_recorder()
        profiling = _profile.active_profile() is not None
        measuring = rec is not None or profiling
        t_dispatch = perf_counter() if measuring else 0.0
        try:
            import jax

            if first:
                owner = self.stats.owner
                if has_sentinel:
                    # value-health checks ride the same cached executable
                    def compute_with_sentinel(s, flags):
                        with jax.named_scope(f"{owner}:compute"):
                            value = traced_compute(m, s)
                        return value, _sentinel.value_flags(flags, value, m)

                    jitted = jax.jit(compute_with_sentinel)
                    example: tuple = (state, sentinel_in)
                else:

                    def compute_only(s):
                        with jax.named_scope(f"{owner}:compute"):
                            return traced_compute(m, s)

                    jitted = jax.jit(compute_only)
                    example = (state,)
                entry = (
                    _costs.aot_compile(
                        jitted, owner=owner, kind="compute", args=example, stats=self.stats
                    ),
                    annotation_scope(owner, "compute", key),
                )
            fn, scope = entry
            if measuring:
                t_dispatch = perf_counter()
            with jax.profiler.TraceAnnotation(scope):
                if has_sentinel:
                    value, sentinel_out = fn(state, sentinel_in)
                else:
                    value = fn(state)
        except Exception as exc:  # noqa: BLE001 — any trace failure demotes to eager
            if not first:
                raise
            classified = _txn.classify_and_demote(
                self._compute_cache, _FALLBACK, self._transient_fails, key, exc
            )
            if isinstance(exc, _Ineligible):
                reason = str(exc)
            elif classified is not None:
                reason = f"compute-dispatch-{classified}"
            else:
                reason = f"compute-trace-failed:{type(exc).__name__}"
            self.stats.fallback(reason)
            return False, None
        if has_sentinel:
            setattr(m, _sentinel.ATTR, sentinel_out)
        if first:
            self._compute_cache[key] = entry
            self.stats.compute_traces += 1
            # prewarm manifest: compute rows carry no specs — prewarm replays
            # them as one compute() per owner against the live topology
            _persist.record_compile(self.stats.owner, "compute")
            fp = _compute_fingerprint(sig, key[1])
            # the sentinel joins the executable's pytree: a toggle must read
            # as treedef-change, not as an unattributed ("unknown") retrace
            fp["treedef"] = (fp["treedef"], has_sentinel)
            cause = _diag.attribute_retrace(fp, self._compute_fps)
            self._compute_fps.append(fp)
            if cause != "initial":
                self.stats.retrace_causes[cause] += 1
            if rec is not None:
                rec.record(
                    "compute.trace" if cause == "initial" else "compute.retrace",
                    self.stats.owner, cause=cause, fused=False,
                )
        else:
            self.stats.compute_cache_hits += 1
        self.stats.compute_dispatches += 1
        dispatch_us = round((perf_counter() - t_dispatch) * 1e6, 3) if measuring else 0.0
        if measuring:
            _hist.observe(self.stats.owner, "compute", "dispatch_us", dispatch_us)
            _hist.observe(self.stats.owner, "compute", "compute_us", dispatch_us)
        device_us = None
        if profiling and not first:
            device_us = completion_probe(value, self.stats.owner, "compute", self.stats, t_dispatch)
        # a cached compute result is an OBSERVATION of the folded watermark
        prov = _lineage.observe_metric(m, "compute")
        if rec is not None:
            span = {} if prov is None or prov.span is None else {"lineage": prov.span}
            rec.record(
                "compute.dispatch", self.stats.owner,
                dispatch_us=dispatch_us, fused=False, cached=not first, **span,
            )
            if device_us is not None:
                rec.record("compute.probe", self.stats.owner, dispatch_us=dispatch_us, device_us=device_us)
        return True, value

    @staticmethod
    def _device_token(state: Dict[str, Any]) -> str:
        # sharding-aware (parallel/sharding.py): a partitioned state keys a
        # different compute executable than its replicated twin
        from torchmetrics_tpu.parallel.sharding import placement_token

        return placement_token(state)


class CollectionEpoch:
    """One packed plan spanning every compute-group owner of a collection."""

    def __init__(self, names: Sequence[str]) -> None:
        self.names: List[str] = list(names)
        self.stats = EngineStats("epoch:collection[" + ",".join(names) + "]")
        self._fold_cache: Dict[Tuple, Any] = {}
        self._fold_fps: List[Dict[str, Any]] = []

    def packed_sync(self, owners: Sequence[Tuple[str, Any]]) -> bool:
        """Sync every owner's states in one exchange; True when handled.

        On success each owner holds its synced (folded) states; the CALLER is
        responsible for the pre-sync snapshots and ``_is_synced`` bookkeeping.
        """
        try:
            plan = PackedSyncPlan(list(owners), _world_size(), None)
        except PackingError as exc:
            self.stats.fallback(f"sync:{exc}")
            return False
        gathered, plan, mode = _exchange(plan, self.stats)
        folded = _run_fold(plan, gathered, self._fold_cache, self.stats, self._fold_fps, mode)
        if folded is None:
            return False
        for name, metric in owners:
            _write_synced(metric, folded.get(name, {}), plan, name)
        self.stats.packed_syncs += 1
        _note_async_sync(self.stats)
        return True
