"""Collection-level dispatch fusion.

A ``MetricCollection`` step over N compute-group leaders costs N dispatches even
when every leader is individually compiled — at pod scale the dispatch floor is
the bottleneck (BENCH_r04: 6.2 ms floor vs 1.7 ms collective marginal at 128
chips). :class:`FusedUpdate` traces every fusable leader's update body into one
``jax.jit`` executable over the combined state pytree ``{name: {state: leaf}}``
with the whole pytree donated, so the N-metric step is a single dispatch and the
members' updates fuse into one XLA program (shared subcomputations — e.g. the
argmax/one-hot of a stat-scores family — dedupe inside XLA instead of being
recomputed per metric).

Members that cannot fuse — list states, a ``compiled_update=False`` opt-out,
an update that fails a cheap per-member ``jax.eval_shape`` trace probe (host
validation, side effects) — are excluded up front and reported back to the
caller to update eagerly; one bad metric never un-fuses the rest.
Shape-bucketing applies when every eligible member supports the pad-subtract
identity (see ``engine/bucketing.py``).
"""

from __future__ import annotations

from time import perf_counter
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from torchmetrics_tpu.diag import costs as _costs
from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import profile as _profile
from torchmetrics_tpu.diag import sentinel as _sentinel
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.engine import bucketing, config
from torchmetrics_tpu.engine import numerics as _numerics
from torchmetrics_tpu.engine import persist as _persist
from torchmetrics_tpu.engine import txn as _txn
from torchmetrics_tpu.engine.compiled import (
    _FALLBACK,
    CompiledUpdate,
    _is_jax_array,
    annotation_scope,
    completion_probe,
    holds_nested_metrics,
    input_signature,
    make_step,
    shield_state,
    state_signature,
    traced_update,
)
from torchmetrics_tpu.engine.stats import EngineStats


def probe_fusable(
    members: Sequence[Tuple[str, Any]],
    states: Dict[str, Dict[str, Any]],
    inputs: Sequence[Any],
    stats: EngineStats,
) -> frozenset:
    """The member names whose update bodies trace abstractly on these inputs.

    The ``jax.eval_shape`` probe runs each member's update abstractly (no XLA
    compile), so one metric with host-side validation or update side effects
    is excluded — with its reason counted — instead of poisoning the whole
    fused executable. Shared by the one-step compile and the scan queue's
    enqueue-time membership resolution.
    """
    import jax

    fusable = []
    for name, m in members:
        try:
            jax.eval_shape(lambda s, *f, _m=m: traced_update(_m, s, f, {}), states[name], *inputs)
            fusable.append(name)
        except Exception as exc:  # noqa: BLE001 — probe failure excludes ONE member
            stats.fallback_reasons[f"member:{name}:{type(exc).__name__}"] += 1
            _diag.record("fused.exclude", stats.owner, member=name, reason=type(exc).__name__)
    return frozenset(fusable)


def build_run_all(
    fusable: Sequence[Tuple[str, Any]],
    comp_names: Dict[str, Tuple[str, ...]],
    quarantined: bool,
):
    """The fused traced body ``run_all(fused_states, flat) -> fused_states``.

    Factored out of :meth:`FusedUpdate._compile` so the scan drain
    (``engine/scan.py``) composes the IDENTICAL dict-of-dicts graph per
    queued step, rider handling included.
    """
    import jax

    def run_all(fused_states, flat):
        import jax.numpy as jnp

        out = {}
        for name, m in fusable:
            mstate = dict(fused_states[name])
            sentinel = mstate.pop(_sentinel.STATE_KEY, None)
            qcount = mstate.pop(_txn.STATE_KEY, None)
            residuals = mstate.pop(_numerics.STATE_KEY, None)
            if residuals is not None:
                # compensated states enter the body zeroed — the body
                # leaves the pure contribution, recomposed in make_step
                zero = comp_names.get(name, ())
                mstate = {
                    k: jnp.zeros_like(v) if k in zero else v for k, v in mstate.items()
                }
            # per-member named_scope: inside the ONE fused executable each
            # member's ops still attribute to their own metric in profiles
            with jax.named_scope(f"{name}:update"):
                updated = traced_update(m, mstate, tuple(flat), {})
            if sentinel is not None:
                # under quarantine the health checks fold over the
                # per-member SELECTED states inside the transaction
                # instead; under compensation over the RECOMPOSED states
                # in build_compensation (the body saw zeroed copies)
                updated[_sentinel.STATE_KEY] = (
                    sentinel
                    if quarantined or residuals is not None
                    else _sentinel.update_flags(sentinel, updated, m)
                )
            if qcount is not None:
                updated[_txn.STATE_KEY] = qcount
            if residuals is not None:
                updated[_numerics.STATE_KEY] = residuals
            out[name] = updated
        return out

    return run_all


def build_fused_riders(fusable: Sequence[Tuple[str, Any]], inputs: Sequence[Any]):
    """``(quarantined, comp_names, step_txn, step_comp)`` for the fused state.

    The dict-of-dicts analogue of ``compiled.build_riders`` — one admission
    plan per member (bounds like ``num_classes`` are per-metric), one
    compensation recomposition per compensated member.
    """
    quarantined = _txn.quarantine_enabled()
    comp_names = {
        name: _numerics.comp_state_names(m)
        for name, m in fusable
        if _numerics.compensation_active(m)
    }
    admissions = (
        {name: _txn.build_admission(m, inputs) for name, m in fusable} if quarantined else {}
    )
    step_txn = None
    if quarantined:

        def step_txn(old_states, result, flat):
            return {
                name: _txn.transact(m, old_states[name], result[name], admissions[name](flat))
                for name, m in fusable
            }

    step_comp = None
    if comp_names:
        comps = {
            name: _numerics.build_compensation(m, comp_names[name], admission=admissions.get(name))
            for name, m in fusable
            if name in comp_names
        }

        def step_comp(old_states, result, flat):
            return {
                name: comps[name](old_states[name], result[name], flat)
                if name in comps
                else result[name]
                for name in result
            }

    return quarantined, comp_names, step_txn, step_comp


class FusedUpdate:
    """One compiled executable updating several metrics' states per step."""

    def __init__(self, metrics: Sequence[Tuple[str, Any]]) -> None:
        self.metrics: List[Tuple[str, Any]] = list(metrics)
        self._cache: Dict[Tuple, Any] = {}
        self._fingerprints: Dict[Tuple, Dict[str, Any]] = {}  # key -> fingerprint (retrace attribution)
        self._transient_fails: Dict[Tuple, int] = {}  # key -> classified-failure count (ladder budget)
        # structural eligibility is frozen per member on first sight, exactly as
        # CompiledUpdate freezes `_disabled_reason` at engine construction —
        # re-walking every member's __dict__ for nested metrics on EVERY step
        # was the dominant warm-path cost in the r09 regression bisect
        self._member_ok: Dict[str, bool] = {}
        self._scan = None  # lazy multi-step queue (engine/scan.py)
        #: set by the owning MetricCollection: re-anchor group views after a
        #: scan drain donates the owners' buffers outside a collection step
        self.on_scan_drain = None
        self.stats = EngineStats("fused:" + ",".join(type(m).__name__ for _, m in self.metrics))

    def eligible_members(self, check_arrays: bool = True) -> List[Tuple[str, Any]]:
        """The members structurally able to fuse right now (opt-outs honored).

        ``check_arrays=False`` skips the per-state array walk — the scan queue
        uses it on non-initial enqueues, where states cannot have changed
        since the queue-start check (only drains write them).
        """
        members: List[Tuple[str, Any]] = []
        for name, m in self.metrics:
            if m.compiled_update is False:  # the per-metric opt-out outranks fusion
                continue
            ok = self._member_ok.get(name)
            if ok is None:
                ok = bool(m._defaults) and not any(
                    isinstance(d, list) for d in m._defaults.values()
                ) and not holds_nested_metrics(m)
                self._member_ok[name] = ok
            if not ok:
                continue
            if check_arrays and not all(_is_jax_array(getattr(m, k)) for k in m._defaults):
                continue
            members.append((name, m))
        return members

    def scan_step(
        self, args: Tuple[Any, ...], kwargs: Dict[str, Any], k: int, async_inflight: Optional[int] = None
    ) -> Optional[Set[str]]:
        """Queue one fused payload for the K-folding scan drain.

        Returns the handled member names (resolved by an abstract trace probe
        at enqueue time), or ``None`` when this step cannot queue — the caller
        runs members individually, and their own per-metric queues apply.
        """
        if self._scan is None:
            from torchmetrics_tpu.engine.scan import FusedScan

            self._scan = FusedScan(self)
        return self._scan.push(args, kwargs, k, async_inflight)

    @staticmethod
    def _fingerprint(state_sig: Tuple, in_sig: Tuple, bucket: Optional[int]) -> Dict[str, Any]:
        """Structured signature digest (see ``compiled.signature_fingerprint``).

        The fused treedef covers member names AND each member's state names —
        a member joining/leaving the fusable set reads as ``treedef-change``.
        Nested rider entries (the compensation residual: ``(key, ((sub, shape,
        dtype), ...))``) flatten into the same aspect tuples.
        """
        names, dtypes, shapes = [], [], []
        for name, sig in state_sig:
            member_names = []
            for entry in sig:
                if len(entry) == 2:  # nested rider
                    member_names.append((entry[0], tuple(n for n, _, _ in entry[1])))
                    dtypes.extend(d for _, _, d in entry[1])
                    shapes.extend(s for _, s, _ in entry[1])
                else:
                    member_names.append(entry[0])
                    shapes.append(entry[1])
                    dtypes.append(entry[2])
            names.append((name, tuple(member_names)))
        return {
            "treedef": tuple(names),
            "dtype": (tuple(dtypes), tuple(d for _, d in in_sig)),
            "shape": (tuple(shapes), tuple(s for s, _ in in_sig)),
            "bucket": bucket,
        }

    def step(self, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Optional[Set[str]]:
        """Run one fused step; returns the set of member names handled.

        ``None`` means nothing was fused — the caller runs every member
        eagerly. A non-empty result may still omit members (they were
        ineligible or failed the trace probe); the caller updates those
        eagerly, and their own per-metric engines still apply.
        """
        st = self.stats
        if kwargs:
            # per-member kwarg filtering inside one executable is not supported;
            # positional calls are the collection hot path
            st.fallback("kwargs")
            return None
        inputs = list(args)
        in_sig = input_signature(inputs)
        if in_sig is None:
            st.fallback("non-array-input")
            return None

        members = self.eligible_members()
        states: Dict[str, Dict[str, Any]] = {}
        for name, m in members:
            mstate = {k: getattr(m, k) for k in m._defaults}
            if _sentinel.sentinel_enabled():
                mstate[_sentinel.STATE_KEY] = _sentinel.ensure_flags(m)
            if _txn.quarantine_enabled():
                mstate[_txn.STATE_KEY] = _txn.ensure_count(m)
            if _numerics.compensation_active(m):
                mstate[_numerics.STATE_KEY] = _numerics.ensure_residuals(m)
            states[name] = mstate
        if len(members) < 2:
            st.fallback("too-few-members")
            return None

        n_pad = 0
        bucketed = False
        bucket: Optional[int] = None
        if config.BUCKETING_ENABLED and all(bucketing.bucket_eligible(m) for _, m in members):
            n = bucketing.batch_size(inputs)
            if n is not None and n > 0:
                bucket = bucketing.next_bucket(n)
                n_pad = bucket - n
                inputs = list(bucketing.pad_args(inputs, bucket))
                in_sig = input_signature(inputs)
                bucketed = True
                st.bucketed_steps += 1
                st.bucket_pad_rows += n_pad
                st.bucket_sizes.add(bucket)

        # dtype OBJECTS, not str(dtype): numpy re-derives the name string on
        # every call (no caching) and the warm loop builds this key per step
        state_sig = tuple((name, state_signature(states[name])) for name, _ in members)
        # placement joins the key (parallel/sharding.py): a member re-placed
        # onto (or off) the state mesh must compile fresh, like a device move
        key = (bucketed, state_sig, in_sig, CompiledUpdate._device_token(states))
        entry = self._cache.get(key)
        if entry is _FALLBACK:
            st.fallback("uncompilable-signature")
            return None

        first = entry is None
        if first:
            try:
                entry = self._compile(members, states, bucketed, inputs, key)
            except Exception as exc:  # noqa: BLE001 — a compile-time failure demotes the key
                # transient resource failures do NOT poison the signature — the
                # members fall back for THIS step (their per-metric engines may
                # ladder down) and the fused path retries later
                classified = _txn.classify_and_demote(
                    self._cache, _FALLBACK, self._transient_fails, key, exc
                )
                st.fallback(
                    f"dispatch-{classified}" if classified else f"trace-failed:{type(exc).__name__}"
                )
                return None
            if entry is None:  # fewer than 2 members survived the trace probes
                self._cache[key] = _FALLBACK
                st.fallback("too-few-traceable-members")
                return None
        fn, donate, fused_names, scope, step_bytes = entry
        fused = [(name, m) for name, m in members if name in fused_names]
        fused_states = {name: states[name] for name, _ in fused}

        if donate:
            fused_states = {
                name: shield_state(fused_states[name], m, st) for name, m in fused
            }

        rec = _diag.active_recorder()
        profiling = _profile.active_profile() is not None
        measuring = rec is not None or profiling
        t_dispatch = perf_counter() if measuring else 0.0
        try:
            import jax

            with jax.profiler.TraceAnnotation(scope):
                if bucketed:
                    out = fn(fused_states, np.int32(n_pad), *inputs)
                else:
                    out = fn(fused_states, *inputs)
        except Exception as exc:  # noqa: BLE001 — a compile-time failure demotes the key
            if not first:
                raise
            classified = _txn.classify_and_demote(
                self._cache, _FALLBACK, self._transient_fails, key, exc
            )
            st.fallback(
                f"dispatch-{classified}" if classified else f"trace-failed:{type(exc).__name__}"
            )
            return None

        if first:
            st.traces += 1
            self._cache[key] = entry
            # prewarm manifest: fused steps are positional-only by contract
            _persist.record_compile(st.owner, "fused", args=inputs, bucket=bucket)
            fused_sig = tuple((name, sig) for name, sig in state_sig if name in fused_names)
            fp = self._fingerprint(fused_sig, in_sig, bucket)
            cause = _diag.attribute_retrace(fp, list(self._fingerprints.values()))
            self._fingerprints[key] = fp
            if cause != "initial":
                st.retrace_causes[cause] += 1
            if rec is not None:
                rec.record(
                    "fused.trace" if cause == "initial" else "fused.retrace",
                    st.owner, cause=cause, bucket=bucket, members=len(fused),
                )
        else:
            st.cache_hits += 1
        st.dispatches += 1
        st.metrics_updated += len(fused)
        if donate:
            st.donated_dispatches += 1
        else:
            st.donation_fallbacks += 1
        # bytes are a pure function of the cache key's shapes/dtypes — computed
        # once at compile time, not re-derived through jax dtype machinery per step
        bytes_moved = step_bytes
        st.bytes_moved += bytes_moved
        dispatch_us = round((perf_counter() - t_dispatch) * 1e6, 3) if measuring else 0.0
        if measuring:
            _hist.observe(st.owner, "fused", "dispatch_us", dispatch_us)
        device_us = None
        if profiling and not first:
            device_us = completion_probe(out, st.owner, "fused", st, t_dispatch)
        if rec is not None:
            rec.record(
                "fused.dispatch", st.owner,
                dispatch_us=dispatch_us,
                donated=donate, bucketed=bucketed, pad_rows=n_pad, bytes=bytes_moved,
                members=len(fused), cached=not first,
            )
            if device_us is not None:
                rec.record("fused.probe", st.owner, dispatch_us=dispatch_us, device_us=device_us)

        handled: Set[str] = set()
        for name, m in fused:
            sentinel_out = out[name].pop(_sentinel.STATE_KEY, None)
            if sentinel_out is not None:
                setattr(m, _sentinel.ATTR, sentinel_out)
            quarantine_out = out[name].pop(_txn.STATE_KEY, None)
            if quarantine_out is not None:
                setattr(m, _txn.ATTR, quarantine_out)
            residual_out = out[name].pop(_numerics.STATE_KEY, None)
            if residual_out is not None:
                setattr(m, _numerics.ATTR, residual_out)
                st.compensated_steps += 1
            for k, v in out[name].items():
                setattr(m, k, v)
            # the wrapped-update bookkeeping the eager path would have done
            m._computed = None
            m._update_count += 1
            handled.add(name)
            if profiling and not first and residual_out is not None:
                # sampled drift audit per compensated member (sanctioned read);
                # the member-qualified owner keeps each member on its own
                # probe cadence despite the shared fused stats block
                _numerics.maybe_drift_probe(m, st, owner=f"{st.owner}:{name}")
        return handled

    def _compile(
        self,
        members: Sequence[Tuple[str, Any]],
        states: Dict[str, Dict[str, Any]],
        bucketed: bool,
        inputs: Sequence[Any],
        key: Tuple,
    ):
        """Probe each member's traceability, then compile the survivors as one step.

        The ``jax.eval_shape`` probe runs the member's update abstractly (no XLA
        compile), so one metric with host-side validation or update side effects
        is excluded — with its reason counted — instead of poisoning the whole
        fused executable.
        """
        import jax

        fused_names = probe_fusable(members, states, inputs, self.stats)
        fusable: List[Tuple[str, Any]] = [(n, m) for n, m in members if n in fused_names]
        if len(fusable) < 2:
            return None

        quarantined, comp_names, step_txn, step_comp = build_fused_riders(fusable, inputs)
        run_all = build_run_all(fusable, comp_names, quarantined)
        example_states = {name: states[name] for name, _ in fusable}
        from torchmetrics_tpu.parallel import sharding as _sharding

        fn, donate = make_step(
            run_all, bucketed, inputs, txn=step_txn, comp=step_comp,
            out_shardings=_sharding.state_out_shardings(example_states),
        )
        # AOT compile for the diag cost ledger (same single trace+compile).
        # tree_leaves-based byte count: rider entries may nest (the residual dict)
        example = (example_states, np.int32(0), *inputs) if bucketed else (example_states, *inputs)
        state_bytes = sum(
            getattr(leaf, "nbytes", 0) for leaf in jax.tree_util.tree_leaves(example_states)
        )
        donated = state_bytes if donate else 0
        fn = _costs.aot_compile(
            fn, owner=self.stats.owner, kind="fused", args=example, donated_bytes=donated,
            stats=self.stats,
        )
        step_bytes = state_bytes + sum(getattr(a, "nbytes", 0) for a in inputs)
        return (
            fn,
            donate,
            frozenset(name for name, _ in fusable),
            annotation_scope(self.stats.owner, "fused", key),
            step_bytes,
        )
