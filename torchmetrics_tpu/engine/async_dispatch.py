"""Async pipelined dispatch — double-buffered background drains for the scan tier.

PR 10's scan queues cut dispatch *count* K-fold, but every drain still runs
synchronously on the caller's thread: at serving QPS the caller pays the full
launch + staging latency on every Kth ``update()``. This module moves the
drain off the caller entirely:

- **Double buffering.** ``update()`` enqueues into the active scan buffer and
  returns immediately; when the buffer reaches K (or a flush point fires) it
  is SWAPPED out under the queue lock — a list pointer exchange, not a
  dispatch — and handed to a bounded background executor that launches the
  SAME cached donated scan executable (``engine/scan.py``) while the caller
  fills the next buffer. Riders (quarantine / compensation / sentinel)
  compose unchanged: the background drain runs the identical
  ``_execute_work`` path the synchronous drain does.
- **The join contract.** The PR-10 flush-on-observation contract becomes a
  *join* contract: every state observation (``compute``/``sync``/
  ``state_dict``/snapshot/scrape) first waits for the in-flight background
  drains of the observed queue, replays any failed payloads on the OBSERVER's
  thread (never the hot loop's), runs the deferred view re-anchors, and only
  then reads state. A reader can still never see state that is K steps stale
  — it just no longer pays the drain on the enqueueing thread.
- **Backpressure, not unbounded memory.** At most ``inflight`` swapped
  buffers may be pending behind the worker; a caller that outruns the drain
  blocks on the OLDEST buffer's completion (counted in
  ``async_backpressure_waits``) instead of growing the queue without bound.
- **Failure = caller replay.** A drain that fails on the worker poisons its
  queue: the failed buffer (and any buffers queued behind it) are handed back
  in FIFO order and replayed step-at-a-time on the next caller-side join —
  the PR-7 ladder semantics. Payloads are never lost and ordering is
  preserved; the replays are counted (``async_replayed_steps``) and the
  fallback reason recorded.
- **Context propagation.** Work items capture ``contextvars.copy_context()``
  at submit, so the worker's events land in the submitting scope's flight
  recorder and the Python-level transfer guard (``diag/transfer_guard.py``)
  stays armed across the thread hop; the native JAX device-to-host guard is
  re-entered on the worker from the propagated mode (it is thread-local).
- **Overlap attribution.** Each background drain records ``overlap_us`` — the
  span of its execution during which NO caller was blocked waiting on it
  (i.e. genuine caller forward progress) — as an ``async.drain`` event the
  PR-5 merged timeline renders as a worker-track slice, plus the aggregate
  ``EngineStats.async_overlap_us``. The packed epoch sync participates too:
  when async mode is on, :func:`note_epoch_sync` stamps the sync's host-side
  completion and the next join attributes the elapsed window (during which
  the next epoch's enqueues proceeded while the sync's device work and
  writeback futures completed) as an ``async.sync.overlap`` event.

Enablement (first hit wins; invalid values FAIL LOUD per the PR-7 env
contract): per-object ``Metric(async_dispatch=)`` /
``MetricCollection(async_dispatch=)`` (``True`` = on with the default
in-flight bound, ``False`` = forced off, int in [1, 16] = explicit bound), an
active :func:`async_context` / :func:`set_async_dispatch` override, then
``TORCHMETRICS_TPU_ASYNC`` (``"1"``/``"on"`` = default bound, ``"0"``/
``"off"``/unset = off, int in [2, 16] = explicit bound). Async dispatch
layers ON the scan tier: it engages only where a scan queue is active
(``scan_steps``/``TORCHMETRICS_TPU_SCAN`` — K >= 2); with scan off there is
no buffer to drain in the background and the knob is inert by design.
"""

from __future__ import annotations

import contextvars
import os
import threading
from collections import deque
from contextlib import contextmanager
from time import perf_counter
from typing import Any, Deque, Generator, List, Optional, Tuple

from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "ASYNC_ENV_VAR",
    "DEFAULT_INFLIGHT",
    "MAX_INFLIGHT",
    "async_context",
    "async_inflight",
    "coerce_inflight",
    "resolve_async",
    "set_async_dispatch",
]

ASYNC_ENV_VAR = "TORCHMETRICS_TPU_ASYNC"

#: default bound on swapped-out buffers pending behind the worker: one drain
#: in flight + one queued behind it while the caller fills the third — the
#: "double buffer" of the design, with one slot of slack for drain jitter
DEFAULT_INFLIGHT = 2

#: hard ceiling: each pending buffer pins K step payloads host-side, so a
#: large bound trades the backpressure guarantee for memory — past ~16 the
#: caller is simply outrunning the device and must be throttled
MAX_INFLIGHT = 16

_UNSET = object()
_override: Any = _UNSET


# ------------------------------------------------------------------ policy


def coerce_inflight(value: Any) -> Optional[int]:
    """Validate an async-dispatch knob: ``0``/``False`` = forced off,
    ``True`` = on with :data:`DEFAULT_INFLIGHT`, int in [1, MAX_INFLIGHT] =
    explicit in-flight bound; ``None`` passes through (defer to the policy)."""
    if value is None:
        return None
    if isinstance(value, bool):
        return DEFAULT_INFLIGHT if value else 0
    if isinstance(value, int):
        if value == 0:
            return 0
        if 1 <= value <= MAX_INFLIGHT:
            return value
    raise TorchMetricsUserError(
        f"async_dispatch must be a bool, 0 (off), or an integer in-flight bound"
        f" in [1, {MAX_INFLIGHT}] (got {value!r})"
    )


def async_inflight() -> Optional[int]:
    """The active in-flight bound, or ``None`` when async dispatch is off.

    An unrecognized ``TORCHMETRICS_TPU_ASYNC`` value fails loud (the PR-7 env
    contract): a typo must not silently disable the overlap it was set to
    enable — nor silently enable a nonsense bound.
    """
    if _override is not _UNSET:
        return _override or None
    raw = os.environ.get(ASYNC_ENV_VAR, "").strip().lower()
    if raw in ("", "0", "off"):
        return None
    if raw in ("1", "on"):
        return DEFAULT_INFLIGHT
    try:
        bound = int(raw)
    except ValueError:
        raise TorchMetricsUserError(
            f"{ASYNC_ENV_VAR}={raw!r} is not a valid async-dispatch setting"
            f" (expected unset/'0'/'off', '1'/'on', or an in-flight bound in"
            f" [2, {MAX_INFLIGHT}])"
        ) from None
    if not (2 <= bound <= MAX_INFLIGHT):
        raise TorchMetricsUserError(
            f"{ASYNC_ENV_VAR}={bound} is out of range: the in-flight bound must"
            f" be in [2, {MAX_INFLIGHT}] ('1' enables the default bound of"
            f" {DEFAULT_INFLIGHT})"
        )
    return bound


def set_async_dispatch(value: Optional[Any]) -> None:
    """Force async dispatch process-wide (``0``/``False`` = off); ``None``
    restores env resolution."""
    global _override
    _override = _UNSET if value is None else coerce_inflight(value)


@contextmanager
def async_context(inflight: Any = True) -> Generator[None, None, None]:
    """Scoped async-dispatch enablement (benches, tests, serving loops).

    Composes with :func:`~torchmetrics_tpu.engine.scan.scan_context` — async
    dispatch drains scan buffers, so a scan depth must be active for it to
    engage. Exiting the scope flushes AND JOINS every queue with pending or
    in-flight work (reason ``async-scope-exit``) — state outside the scope is
    never stale and no drain outlives its enablement — then restores the
    previous policy.
    """
    global _override
    prev = _override
    _override = coerce_inflight(inflight)
    try:
        yield
    finally:
        try:
            from torchmetrics_tpu.engine.scan import flush_all

            # drain() joins in-flight work before (and instead of) a
            # caller-side dispatch while async mode is still on
            flush_all("async-scope-exit")
        finally:
            _override = prev


def resolve_async(kwarg: Optional[Any]) -> Optional[int]:
    """Per-object resolution: the coerced ``async_dispatch`` kwarg wins
    (``0`` = forced off), else the process policy. Mirrors
    ``Metric._scan_depth``'s kwarg-over-context-over-env order."""
    if kwarg is not None:
        return kwarg or None  # already coerced at construction; 0 = off
    return async_inflight()


# ------------------------------------------------------------------ executor


class _AsyncExecutor:
    """One daemon worker draining swapped-out scan buffers in global FIFO.

    A single worker is the ordering guarantee: buffers of one queue can never
    reorder, and cross-queue work shares the device serially exactly like the
    synchronous path. The executor holds no queue locks — work items carry
    everything the drain needs (see ``engine/scan.py:_DrainWork``) and state
    writeback serializes on the per-queue drain mutex.
    """

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self._items: Deque[Any] = deque()  # guarded-by: _cv
        self._thread: Optional[threading.Thread] = None  # guarded-by: _cv

    def submit(self, work: Any) -> None:
        with self._cv:
            # lazily (re)started: survives fork-per-test process models where
            # a child inherits the module state but not the running thread
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="tm-tpu-async-drain", daemon=True
                )
                self._thread.start()
            self._items.append(work)
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._items:
                    self._cv.wait()
                work = self._items.popleft()
            try:
                # the copied context carries the submitting scope's flight
                # recorder and transfer-guard mode across the thread hop
                work.ctx.run(work.queue._worker_execute, work)
            finally:
                work.done.set()


_EXECUTOR = _AsyncExecutor()

#: latched on the first submit: lets env-silent call sites (the epoch sync
#: stamp) know async ever engaged WITHOUT consulting the env knob — an invalid
#: TORCHMETRICS_TPU_ASYNC must only raise where the policy is actually read
#: (the enqueue path), never on a sync that predates any async use
_engaged = False


def submit(work: Any) -> None:
    """Hand one swapped-out buffer to the background worker (FIFO)."""
    global _engaged
    _engaged = True
    work.ctx = contextvars.copy_context()
    _EXECUTOR.submit(work)


# ------------------------------------------------------------- sync overlap

#: pending epoch-sync overlap stamps: (EngineStats, host-completion ts). The
#: next join consumes them; bounded so an observation-free loop cannot grow it
_SYNC_NOTES: List[Tuple[Any, float]] = []  # guarded-by: _SYNC_NOTES_LOCK
_SYNC_NOTES_LOCK = threading.Lock()
_SYNC_NOTES_CAP = 64


def note_epoch_sync(stats: Any) -> None:
    """Stamp a packed epoch sync's host-side completion for overlap credit.

    Called by ``engine/epoch.py`` after the packed exchange + fold dispatch
    return (the written states are still device FUTURES at this point). When
    async mode is on, the elapsed window until the next join — during which
    the caller's next-epoch enqueues proceeded while the sync's device work
    completed — is attributed as ``async.sync.overlap``. Env-silent: gated on
    the engaged latch ONLY, never the knob — a kwarg-engaged process with a
    typo'd TORCHMETRICS_TPU_ASYNC must not crash its epoch syncs (the env
    fails loud where it is resolved: the enqueue path). A stamp recorded
    after async dispatch was later disabled credits a window the caller did
    spend making forward progress — generous but bounded (the notes cap) and
    consumed at the next join either way.
    """
    if not _engaged:
        return
    with _SYNC_NOTES_LOCK:
        if len(_SYNC_NOTES) >= _SYNC_NOTES_CAP:
            _SYNC_NOTES.pop(0)
        _SYNC_NOTES.append((stats, perf_counter()))


def consume_sync_notes() -> None:
    """Credit every pending sync stamp's overlap window at a join point."""
    with _SYNC_NOTES_LOCK:
        if not _SYNC_NOTES:
            return
        notes, _SYNC_NOTES[:] = list(_SYNC_NOTES), []
    from torchmetrics_tpu.diag import trace as _diag

    now = perf_counter()
    for stats, t0 in notes:
        overlap_us = int((now - t0) * 1e6)
        stats.async_overlap_us += overlap_us
        _diag.record("async.sync.overlap", stats.owner, overlap_us=overlap_us)
