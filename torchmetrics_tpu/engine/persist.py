"""Persistent executable cache + prewarm manifests — zero-cold-start serving.

Every deploy/restart used to pay the full XLA compile bill before the first
request: the engines lower everything through the AOT chain
(``diag/costs.py:aot_compile``) and the ledger records seconds of
``compile_ms`` per signature, but none of it survived the process. This module
makes the warm state durable:

- **Persistent executable cache** — each compiled :class:`jax.stages.Compiled`
  serializes via ``jax.experimental.serialize_executable`` into an atomic
  artifact (``.tmp`` + ``os.replace``, the ``parallel/elastic.py`` snapshot
  contract) keyed by the existing ``(owner, kind, signature)`` fingerprint
  extended with a **compatibility envelope** (jax/jaxlib version, backend,
  device kind/count, mesh shape, x64 flag). A stale or cross-topology
  artifact is a COUNTED miss, never a wrong load: envelope mismatches raise
  :class:`PersistEnvelopeError`, corrupt payloads :class:`PersistIntegrityError`,
  and both degrade loud (``persist.fallback`` event + counter) to a fresh
  compile. Backends whose executables do not serialize fall back to enabling
  JAX's native compilation cache in the same directory — recorded, once.
- **Signature manifest** — every engine compile appends one JSON line
  (owner, kind, signature, input specs, bucket / K-bucket coords) to
  ``manifest.jsonl``; :func:`prewarm` replays the full signature set — bucket
  ladder, K-buckets, fold/compute graphs — at deploy time before traffic
  lands, loading from the persistent cache where hits exist and compiling
  (then persisting) the rest. Replays run against zero-filled inputs with the
  metric's live state snapshotted (device-side copies) and restored after, so
  prewarm is value-inert.
- **Warm-replica handoff** — :func:`warm_start` composes :func:`prewarm` with
  :func:`~torchmetrics_tpu.parallel.elastic.restore_latest` so a replacement
  pod is serving-identical — states restored, executables hot — in one call
  (wired through ``serve/sidecar.py`` startup).

Enablement rides ``TORCHMETRICS_TPU_PERSIST=<dir>`` (:func:`persist_dir` is
the one registered fail-loud parser — the PR-7 env contract) or the scoped
:func:`persist_context` / :func:`set_persist_dir` overrides. The load path is
transfer-free by design: artifacts deserialize from disk to device without a
single device→host read, so it runs clean under the diag STRICT guard.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import zlib
from contextlib import contextmanager
from typing import Any, Dict, Generator, List, Optional, Sequence, Tuple

from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "PERSIST_ENV_VAR",
    "PersistEnvelopeError",
    "PersistIntegrityError",
    "compat_envelope",
    "load_executable",
    "load_manifest",
    "persist_context",
    "persist_dir",
    "persist_state",
    "prewarm",
    "record_compile",
    "reset_persist_stats",
    "set_persist_dir",
    "store_executable",
    "warm_start",
]

#: env knob: a directory path enables the persistent cache; ``"0"``/``"off"``
#: disable explicitly; an empty value fails loud (the PR-7 env contract)
PERSIST_ENV_VAR = "TORCHMETRICS_TPU_PERSIST"

#: artifact + manifest format — bumped on any layout change so an old-format
#: file is a typed rejection, never a mis-parse
PERSIST_FORMAT_VERSION = 1

_UNSET = object()
_dir_override: Any = _UNSET


class PersistIntegrityError(TorchMetricsUserError):
    """A persisted artifact is unreadable/corrupt (truncated, CRC mismatch)."""


class PersistEnvelopeError(TorchMetricsUserError):
    """A persisted artifact's compatibility envelope does not match this process."""


def persist_dir() -> Optional[str]:
    """The active persistent-cache directory, or ``None`` (persistence off).

    Resolution: :func:`set_persist_dir` / :func:`persist_context` override
    first, then ``TORCHMETRICS_TPU_PERSIST``. The env value is a directory
    path (created on demand); ``"0"``/``"off"`` disable explicitly; an empty/
    whitespace value raises — a half-set knob must never silently disable.
    """
    if _dir_override is not _UNSET:
        return _dir_override
    raw = os.environ.get(PERSIST_ENV_VAR)
    if raw is None:
        return None
    value = raw.strip()
    if not value:
        raise TorchMetricsUserError(
            f"Invalid {PERSIST_ENV_VAR}={raw!r}: expected a cache directory path"
            " (or '0'/'off' to disable explicitly). Unset the variable to disable."
        )
    if value.lower() in ("0", "off"):
        return None
    return value


def set_persist_dir(directory: Optional[str]) -> None:
    """Force the cache directory process-wide; ``None`` disables, and
    :func:`reset_persist_overrides` semantics ride ``persist_context``."""
    global _dir_override
    _dir_override = directory


@contextmanager
def persist_context(directory: Optional[str]) -> Generator[None, None, None]:
    """Scoped persistent-cache enablement (tests, the coldstart bench)."""
    global _dir_override
    prev = _dir_override
    _dir_override = directory
    try:
        yield
    finally:
        _dir_override = prev


# ------------------------------------------------------------------ counters

_LOCK = threading.Lock()

#: process-wide monotonic counters (compiles can land from the async worker
#: thread, so every bump takes the lock; the hot dispatch loop never touches
#: these — persistence is compile-time-only machinery)
_COUNTERS: Dict[str, float] = {  # guarded-by: _LOCK
    "hits": 0,
    "misses": 0,
    "stores": 0,
    "stored_bytes": 0,
    "deserialize_ms": 0.0,
    "envelope_rejects": 0,
    "corrupt_skips": 0,
    "fallbacks": 0,
    "prewarm_replays": 0,
    "manifest_entries": 0,
}

# manifest dedup: directory -> set of (owner, kind, signature) already on disk
_MANIFEST_SEEN: Dict[str, set] = {}  # guarded-by: _LOCK

# one-shot flag: the native-compilation-cache fallback engaged for this process
_native_fallback = False


def _bump(**deltas: float) -> None:
    with _LOCK:
        for key, delta in deltas.items():
            _COUNTERS[key] += delta


def persist_state() -> Dict[str, Any]:
    """One JSON-serializable dict for telemetry: counters + enablement."""
    with _LOCK:
        out: Dict[str, Any] = dict(_COUNTERS)
    out["deserialize_ms"] = round(out["deserialize_ms"], 3)
    try:
        directory = persist_dir()
    except TorchMetricsUserError:
        directory = None
    out["enabled"] = directory is not None
    out["native_fallback"] = _native_fallback
    return out


def reset_persist_stats() -> None:
    """Zero the counters (``reset_engine_stats`` calls this); the on-disk
    cache and the manifest dedup sets are durable state and stay."""
    with _LOCK:
        for key in _COUNTERS:
            _COUNTERS[key] = 0.0 if key == "deserialize_ms" else 0


# ------------------------------------------------------------------ envelope


def compat_envelope() -> Dict[str, Any]:
    """The compatibility envelope a persisted executable must match exactly.

    Everything that can make a serialized XLA executable wrong to load:
    jax/jaxlib version (binary format), backend platform + device kind/count
    (target ISA + topology), the active state-mesh shape (SPMD partitioning
    compiled into the program), and the x64 flag (dtype promotion baked into
    the traced graph).
    """
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "")
    except Exception:  # noqa: BLE001 — jaxlib version is advisory metadata
        jaxlib_version = ""
    devices = jax.devices()
    from torchmetrics_tpu.parallel.sharding import metric_mesh

    try:
        mesh = metric_mesh()
    except TorchMetricsUserError:
        mesh = None
    mesh_shape = "" if mesh is None else "x".join(f"{k}={v}" for k, v in sorted(dict(mesh.shape).items()))
    return {
        "format": PERSIST_FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "",
        "device_count": len(devices),
        "mesh": mesh_shape,
        "x64": bool(jax.config.jax_enable_x64),
    }


def _envelope_digest(envelope: Dict[str, Any]) -> str:
    payload = json.dumps(envelope, sort_keys=True).encode()
    return format(zlib.crc32(payload) & 0xFFFFFFFF, "08x")


def _artifact_path(directory: str, owner: str, kind: str, signature: str) -> str:
    import hashlib

    digest = hashlib.sha256(
        f"{owner}|{kind}|{signature}|{_envelope_digest(compat_envelope())}".encode()
    ).hexdigest()[:32]
    return os.path.join(directory, "executables", f"{digest}.tmx")


# ------------------------------------------------------------------ artifacts


def _atomic_write(path: str, payload: bytes) -> None:
    """The ``parallel/elastic.py`` snapshot contract: ``.tmp`` + flush +
    fsync + ``os.replace`` — a reader never observes a torn artifact."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(payload)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _enable_native_fallback(directory: str, reason: str) -> None:
    """Serialization unsupported on this backend: enable JAX's own persistent
    compilation cache in the same directory instead — the compile is still
    amortized across processes, just without the manifest-driven deserialize
    fast path. Recorded once."""
    global _native_fallback
    with _LOCK:
        if _native_fallback:
            return
        _native_fallback = True
    _bump(fallbacks=1)
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(directory, "xla-cache"))
    _diag.record("persist.fallback", "persist", reason=f"native-cache:{reason}")


def store_executable(owner: str, kind: str, signature: str, compiled: Any) -> bool:
    """Serialize + atomically persist one compiled executable; True on store.

    A serialization failure (backend without ``serialize_executable`` support)
    degrades to the native-compilation-cache fallback — counted, never raised
    into the engine's compile path.
    """
    directory = persist_dir()
    if directory is None:
        return False
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        record = {
            "format": PERSIST_FORMAT_VERSION,
            "envelope": compat_envelope(),
            "owner": owner,
            "kind": kind,
            "signature": signature,
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
            "crc": zlib.crc32(payload) & 0xFFFFFFFF,
        }
        blob = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
        _atomic_write(_artifact_path(directory, owner, kind, signature), blob)
    except Exception as exc:  # noqa: BLE001 — persistence must never fail a compile
        _enable_native_fallback(directory, f"{type(exc).__name__}: {exc}")
        return False
    _bump(stores=1, stored_bytes=len(blob))
    _diag.record("persist.save", owner, exe_kind=kind, signature=signature, bytes=len(blob))
    return True


def load_executable(owner: str, kind: str, signature: str) -> Optional[Any]:
    """Load one persisted executable, or ``None`` when no artifact exists.

    Raises :class:`PersistIntegrityError` (unreadable / truncated / CRC
    mismatch / undeserializable) or :class:`PersistEnvelopeError` (format or
    compatibility-envelope mismatch — a stale or cross-topology artifact).
    The engine path catches both via :func:`try_load_executable`; tests call
    this directly to assert the typed rejection.
    """
    directory = persist_dir()
    if directory is None:
        return None
    path = _artifact_path(directory, owner, kind, signature)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as fh:
            record = pickle.loads(fh.read())
        if not isinstance(record, dict):
            raise TypeError(f"artifact root is {type(record).__name__}, expected dict")
    except Exception as exc:  # noqa: BLE001 — any unpickle failure is corruption
        raise PersistIntegrityError(
            f"persisted executable {os.path.basename(path)} is unreadable:"
            f" {type(exc).__name__}: {exc}"
        ) from exc
    if record.get("format") != PERSIST_FORMAT_VERSION:
        raise PersistEnvelopeError(
            f"persisted executable {os.path.basename(path)} has format"
            f" {record.get('format')!r}, expected {PERSIST_FORMAT_VERSION}"
        )
    envelope = compat_envelope()
    if record.get("envelope") != envelope:
        stale = {
            key: (record.get("envelope", {}).get(key), envelope[key])
            for key in envelope
            if record.get("envelope", {}).get(key) != envelope[key]
        }
        raise PersistEnvelopeError(
            f"persisted executable {os.path.basename(path)} was compiled for a"
            f" different environment: {stale}"
        )
    payload = record.get("payload", b"")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != record.get("crc"):
        raise PersistIntegrityError(
            f"persisted executable {os.path.basename(path)} failed its payload CRC"
        )
    try:
        from jax.experimental.serialize_executable import deserialize_and_load

        return deserialize_and_load(payload, record["in_tree"], record["out_tree"])
    except Exception as exc:  # noqa: BLE001 — an undeserializable artifact is corruption
        raise PersistIntegrityError(
            f"persisted executable {os.path.basename(path)} failed to deserialize:"
            f" {type(exc).__name__}: {exc}"
        ) from exc


def try_load_executable(owner: str, kind: str, signature: str) -> Optional[Any]:
    """The engine-facing load: a hit returns the executable (counted), every
    rejection — absent, stale envelope, corrupt — degrades to ``None``
    (a counted miss), LOUD via the flight recorder, never a wrong load."""
    from time import perf_counter

    t0 = perf_counter()
    try:
        compiled = load_executable(owner, kind, signature)
    except PersistEnvelopeError as exc:
        _bump(envelope_rejects=1, misses=1)
        _diag.record("persist.fallback", owner, exe_kind=kind, reason=f"envelope:{exc}")
        return None
    except PersistIntegrityError as exc:
        _bump(corrupt_skips=1, misses=1)
        _diag.record("persist.fallback", owner, exe_kind=kind, reason=f"corrupt:{exc}")
        return None
    if compiled is None:
        _bump(misses=1)
        return None
    ms = (perf_counter() - t0) * 1e3
    _bump(hits=1, deserialize_ms=ms)
    _diag.record("persist.load", owner, exe_kind=kind, signature=signature, deserialize_ms=round(ms, 3))
    return compiled


# ------------------------------------------------------------------ manifest


def _manifest_path(directory: str) -> str:
    return os.path.join(directory, "manifest.jsonl")


def _spec(value: Any) -> List[Any]:
    return [list(getattr(value, "shape", ())), str(getattr(value, "dtype", type(value).__name__))]


def _row_signature(row: Dict[str, Any]) -> str:
    body = json.dumps(
        [row.get("owner"), row.get("kind"), row.get("args"), row.get("kw"),
         row.get("bucket"), row.get("k")],
        sort_keys=True,
    ).encode()
    return format(zlib.crc32(body) & 0xFFFFFFFF, "08x")


def load_manifest(directory: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every recorded manifest row, in append order. Corrupt lines (torn
    writes, foreign content) are skipped LOUD — counted + recorded — so one
    bad line can never void a whole deploy's prewarm set."""
    directory = persist_dir() if directory is None else directory
    if directory is None:
        return []
    path = _manifest_path(directory)
    if not os.path.exists(path):
        return []
    rows: List[Dict[str, Any]] = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
                if not isinstance(row, dict) or "owner" not in row or "kind" not in row:
                    raise ValueError("not a manifest row")
            except (json.JSONDecodeError, ValueError) as exc:
                _bump(corrupt_skips=1)
                _diag.record(
                    "persist.fallback", "persist",
                    reason=f"manifest-line-{lineno}:{type(exc).__name__}",
                )
                continue
            rows.append(row)
    return rows


def record_compile(
    owner: str,
    kind: str,
    args: Optional[Sequence[Any]] = None,
    kw: Optional[Dict[str, Any]] = None,
    bucket: Optional[int] = None,
    k: Optional[int] = None,
) -> None:
    """Append one (owner, kind, signature, specs, bucket/K coords) manifest
    row — called by each engine's first-compile success block. Dedup is
    in-memory per directory, seeded from the on-disk manifest so restarts do
    not re-append the rows they replay. No-op with persistence off."""
    directory = persist_dir()
    if directory is None:
        return
    row: Dict[str, Any] = {
        "format": PERSIST_FORMAT_VERSION,
        "owner": owner,
        "kind": kind,
        "args": [_spec(a) for a in args] if args is not None else None,
        "kw": {name: _spec(v) for name, v in sorted(kw.items())} if kw else None,
        "bucket": bucket,
        "k": k,
    }
    row["sig"] = _row_signature(row)
    dedup_key = (owner, kind, row["sig"])
    with _LOCK:
        seen = _MANIFEST_SEEN.get(directory)
        if seen is None:
            seen = _MANIFEST_SEEN[directory] = set()
            preload = True
        else:
            preload = False
    if preload:
        for existing in load_manifest(directory):
            seen.add((existing.get("owner"), existing.get("kind"), existing.get("sig")))
    with _LOCK:
        if dedup_key in seen:
            return
        seen.add(dedup_key)
        os.makedirs(directory, exist_ok=True)
        with open(_manifest_path(directory), "a") as fh:
            fh.write(json.dumps(row, sort_keys=True) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
    _bump(manifest_entries=1)
    _diag.record("persist.manifest", owner, exe_kind=kind, signature=row["sig"], bucket=bucket, k=k)


# ------------------------------------------------------------------ prewarm


def _zeros(spec: Sequence[Any]) -> Any:
    import jax
    import numpy as np

    # device_put of a host buffer, NOT jnp.zeros: zeros-via-XLA compiles one
    # tiny graph per unique shape, which on a replica prewarming dozens of
    # signatures costs more than the deserializes it feeds (~10 ms each)
    shape, dtype = spec
    return jax.device_put(np.zeros(tuple(shape), dtype=np.dtype(dtype)))


_RIDER_ATTRS = ("_sentinel_flags", "_quarantined_count", "_comp_residuals")


def _snapshot_metric(metric: Any) -> Dict[str, Any]:
    """Device-side copies of everything a replay could mutate: registered
    states (donation-proof — ``.copy()`` allocates fresh buffers on device,
    no host transfer), rider buffers, and the update bookkeeping."""

    def _copy(value: Any) -> Any:
        if isinstance(value, list):
            return [_copy(v) for v in value]
        if isinstance(value, dict):
            return {name: _copy(v) for name, v in value.items()}
        return value.copy() if hasattr(value, "copy") else value

    saved: Dict[str, Any] = {"states": {}, "riders": {}, "absent": []}
    for attr in metric._defaults:
        saved["states"][attr] = _copy(getattr(metric, attr))
    for attr in _RIDER_ATTRS:
        if attr in metric.__dict__:
            saved["riders"][attr] = _copy(metric.__dict__[attr])
        else:
            saved["absent"].append(attr)
    saved["update_count"] = getattr(metric, "_update_count", None)
    saved["computed"] = getattr(metric, "_computed", None)
    return saved


def _restore_metric(metric: Any, saved: Dict[str, Any]) -> None:
    for attr, value in saved["states"].items():
        setattr(metric, attr, value)
    for attr, value in saved["riders"].items():
        metric.__dict__[attr] = value
    for attr in saved["absent"]:
        metric.__dict__.pop(attr, None)
    if saved["update_count"] is not None:
        metric._update_count = saved["update_count"]
    metric._computed = saved["computed"]


def _target_metrics(obj: Any) -> List[Any]:
    if hasattr(obj, "_defaults"):  # duck-typed Metric
        return [obj]
    if hasattr(obj, "_modules"):  # duck-typed MetricCollection
        return list(obj._modules.values())
    raise TorchMetricsUserError(
        f"prewarm expects a Metric or MetricCollection, got {type(obj).__name__}"
    )


def _replay_row(obj: Any, row: Dict[str, Any], computed_owners: set) -> bool:
    """Replay ONE manifest row against ``obj``; True when it dispatched.

    update/scan rows replay through the metric's public ``update`` (scan rows
    inside a ``scan_context(K)`` so the drain compiles the recorded K-bucket);
    fused rows through the collection's ``update``; compute-family rows
    (compute / sync-compute / sync-fold) through ONE ``compute()`` per owner —
    the graphs the CURRENT topology needs, so a cross-world manifest row can
    never force a wrong-mesh replay.
    """
    kind = row.get("kind")
    owner = row.get("owner", "")
    args = [_zeros(spec) for spec in row.get("args") or []]
    kw = {name: _zeros(spec) for name, spec in (row.get("kw") or {}).items()}

    if kind in ("update", "scan", "fused"):
        # resolve the row's owner to a replay target: a "fused:A,B" owner
        # names the collection's GROUP REPRESENTATIVES (engine/fusion.py
        # builds FusedUpdate over one metric per compute group), so it
        # matches any collection whose member types cover those names; a
        # bare owner is a metric type name resolved through the members
        fused_target = owner.startswith("fused:")
        if fused_target:
            if not hasattr(obj, "_modules"):
                return False
            member_types = {type(m).__name__ for m in obj._modules.values()}
            if not set(owner[len("fused:"):].split(",")) <= member_types:
                return False
            target: Any = obj
        else:
            target = next(
                (m for m in _target_metrics(obj) if type(m).__name__ == owner), None
            )
            if target is None:
                return False
        if kind == "scan":
            from torchmetrics_tpu.engine.scan import flush_metrics, scan_context

            kb = int(row.get("k") or 8)
            with scan_context(k=kb):
                for _ in range(kb):
                    target.update(*args, **kw)
                flush_metrics(list(_target_metrics(obj)), "prewarm")
        else:
            target.update(*args, **kw)
        return True
    if kind in ("compute", "sync-compute", "sync-fold"):
        if owner in computed_owners:
            return False
        if hasattr(obj, "_modules") and owner.startswith("epoch:collection["):
            target = obj
        else:
            target = next(
                (m for m in _target_metrics(obj) if owner == f"epoch:{type(m).__name__}"),
                None,
            )
        if target is None:
            return False
        computed_owners.add(owner)
        target.compute()
        return True
    return False


def prewarm(obj: Any, directory: Optional[str] = None, manifest: Optional[List[Dict[str, Any]]] = None) -> Dict[str, Any]:
    """Replay the recorded signature manifest so every executable is hot
    BEFORE traffic lands — persistent-cache hits deserialize in O(load),
    misses compile once and persist for the next replica.

    Value-inert: live state (registered states, rider buffers, update
    bookkeeping) is snapshotted device-side before the replays and restored
    after, and scan queues are flushed inside the replay scope. Failed
    replays are counted + recorded (``persist.fallback``), never raised —
    a half-warm replica must still serve.
    """
    directory = persist_dir() if directory is None else directory
    report: Dict[str, Any] = {"entries": 0, "replayed": 0, "skipped": 0, "failed": 0}
    if directory is None:
        return report
    rows = load_manifest(directory) if manifest is None else list(manifest)
    report["entries"] = len(rows)
    if not rows:
        return report
    before = persist_state()
    metrics = _target_metrics(obj)
    saved = [_snapshot_metric(m) for m in metrics]
    computed_owners: set = set()
    import warnings

    with persist_context(directory):
        try:
            # the replay is a deliberate value-inert probe: compute-before-
            # update style advisories would fire on every compute-family row
            # and mean nothing here (state is snapshotted/restored around us)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", UserWarning)
                for row in rows:
                    try:
                        if _replay_row(obj, row, computed_owners):
                            report["replayed"] += 1
                        else:
                            report["skipped"] += 1
                    except Exception as exc:  # noqa: BLE001 — a half-warm replica must serve
                        report["failed"] += 1
                        _diag.record(
                            "persist.fallback", row.get("owner", ""),
                            exe_kind=row.get("kind", ""), reason=f"replay:{type(exc).__name__}: {exc}",
                        )
        finally:
            for m, snap in zip(metrics, saved):
                _restore_metric(m, snap)
    after = persist_state()
    report["hits"] = int(after["hits"] - before["hits"])
    report["misses"] = int(after["misses"] - before["misses"])
    _bump(prewarm_replays=report["replayed"])
    # attribute the replays to ONE live engine so engine_report() carries
    # them: the collection's fused engine when fused dispatch built one,
    # else the first member metric's compiled-update engine
    for holder in (getattr(obj, "_fused_engine", None), *(
        getattr(m, "_engine", None) for m in metrics
    )):
        if holder is not None:
            holder.stats.prewarm_replays += report["replayed"]
            break
    _diag.record(
        "persist.prewarm", type(obj).__name__,
        entries=report["entries"], replayed=report["replayed"], skipped=report["skipped"],
        failed=report["failed"], hits=report["hits"], misses=report["misses"],
    )
    return report


def warm_start(
    obj: Any,
    directory: Optional[str] = None,
    snapshot_dir: Optional[str] = None,
    rank: int = 0,
    world_size: int = 1,
) -> Dict[str, Any]:
    """Warm-replica handoff in one call: :func:`prewarm` the full executable
    set, then :func:`~torchmetrics_tpu.parallel.elastic.restore_latest` the
    newest durable snapshot — the replacement pod is serving-identical
    (states restored, executables hot) before it answers its first request.

    Prewarm runs FIRST so the restore lands on an already-hot compute path;
    snapshot-restore errors propagate (they are the elastic layer's typed
    contract), prewarm failures degrade loud per row.
    """
    report = prewarm(obj, directory)
    if snapshot_dir is not None:
        from torchmetrics_tpu.parallel.elastic import restore_latest

        report["restored_seq"] = restore_latest(obj, snapshot_dir, rank=rank, world_size=world_size)
    return report
