"""Transactional state integrity — in-graph batch admission, quarantine, and
the dispatch-failure fallback ladder.

PR 4's sentinels detect a NaN **after** it has already destroyed a donated
accumulator; this module prevents the destruction. Three pieces:

- **Admission prelude** (:func:`build_admission`): a jittable per-batch check
  compiled INTO the update executable — finite-check on float/complex inputs,
  ``[0, num_classes)`` range bounds on integer label inputs of metrics that
  declare ``num_classes`` — producing one traced boolean *poison flag* per
  batch. No host transfer: the flag is data inside the graph, never read in
  the hot loop.
- **Transaction** (:func:`transact`): the state write becomes
  ``jnp.where(poisoned, old, new)`` inside the SAME donated graph, so a
  poisoned batch is **quarantined** — the accumulator keeps its pre-batch
  values bit-exactly — instead of corrupting state. A per-metric device
  counter (``metric._quarantined_count``, pytree key ``__quarantine__``)
  increments in-graph; it reaches the host only at the sanctioned
  :func:`read_quarantine` boundary (epoch end), where the delta lands in
  ``EngineStats.quarantined_batches`` and an ``update.quarantine`` event.
  With the sentinel enabled, a quarantined batch raises the dedicated
  ``input_poisoned`` bit (``diag/sentinel.py``) while the ``nan``/``inf``
  bits stay clear — "input was poisoned, state is clean" is distinguishable
  from sticky state corruption at every surface.
- **Fallback ladder** (:func:`classify_dispatch_error` + the engines): a
  dispatch-time ``XlaRuntimeError`` / ``RESOURCE_EXHAUSTED`` on a fresh
  bucket no longer aborts the step OR permanently poisons the signature
  cache — the per-metric engine retries the next-smaller bucket (the batch
  splits into half-bucket chunks, exact for the row-additive metrics
  bucketing admits), then falls back to eager for this step only. Counted
  (``EngineStats.ladder_retries``, ``update.ladder`` events), typed
  (classified reason strings), never a crashed step.

Modes (``TORCHMETRICS_TPU_QUARANTINE`` / :func:`quarantine_context`, first
hit wins — override, then env):

==========  ==============================================================
``0``/unset  off — zero machinery on every path (the default)
``1``        quarantine — poisoned batches are skipped in-graph, counted
``error``    fail loud — the admission check runs on the HOST before any
             state mutation and raises :class:`QuarantinedBatchError`
             (one sanctioned device sync per step, by explicit request)
==========  ==============================================================

Enable the same mode on EVERY rank of a world: the quarantine counter rides
the packed sync's reduce buffer (``parallel/packing.py`` sums it cross-rank,
exactly like ``_update_count`` folds at checkpoint restore), so asymmetric
enablement would desynchronize the buffer layout — the same rule the
sentinel and the audit already document.
"""

from __future__ import annotations

import os
import weakref
from contextlib import contextmanager
from typing import Any, Callable, Dict, Generator, List, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu.diag import lineage as _lineage
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "ATTR",
    "MODE_ERROR",
    "MODE_OFF",
    "MODE_QUARANTINE",
    "QUARANTINE_ENV_VAR",
    "QuarantinedBatchError",
    "STATE_KEY",
    "admission_check_or_raise",
    "build_admission",
    "classify_and_demote",
    "classify_dispatch_error",
    "eager_apply",
    "eager_update",
    "ensure_count",
    "quarantine_context",
    "quarantine_enabled",
    "quarantine_error",
    "quarantine_mode",
    "quarantine_report",
    "read_quarantine",
    "reset_quarantine",
    "set_quarantine_mode",
    "transact",
]

QUARANTINE_ENV_VAR = "TORCHMETRICS_TPU_QUARANTINE"

#: reserved pytree key for the quarantine counter inside compiled step states —
#: aliased from the canonical declaration (engine/statespec.py RIDER_KEYS);
#: tmlint rule TM301 forbids respelling the literal outside that module
from torchmetrics_tpu.engine.statespec import QUARANTINE_KEY as STATE_KEY  # noqa: E402
#: the attribute carrying the live device counter on a metric instance
ATTR = "_quarantined_count"

MODE_OFF = "0"
MODE_QUARANTINE = "1"
MODE_ERROR = "error"

_mode_override: Optional[str] = None
# (raw env value, parsed mode) — see quarantine_mode()
_env_mode_cache: tuple = ("", MODE_OFF)

# metrics currently carrying a quarantine counter, for process-wide reporting.
# WeakValueDictionary keyed by id(): Metric.__hash__ covers current state-array
# ids, so a hash-based WeakSet would leak one entry per update (the sentinel
# registry documents the same trap).
_REGISTRY: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


class QuarantinedBatchError(TorchMetricsUserError):
    """``TORCHMETRICS_TPU_QUARANTINE=error``: a batch failed admission.

    Raised BEFORE any state mutation — the metric's accumulator and
    ``update_count`` are untouched, on the compiled and the eager path alike.
    """


# ------------------------------------------------------------------ policy


def quarantine_mode() -> str:
    """The active mode: :data:`MODE_OFF` / :data:`MODE_QUARANTINE` / :data:`MODE_ERROR`.

    An unrecognized env value fails loud: a typo must not silently disable the
    protection the knob was set to enable (same contract as
    ``SnapshotPolicy.from_env``).
    """
    global _env_mode_cache
    if _mode_override is not None:
        return _mode_override
    # cached parse keyed on the raw value: this sits on the per-update hot
    # path (the wrapper consults the mode every step), so a steady env var
    # costs one os.environ read + string compare, not a re-parse
    raw = os.environ.get(QUARANTINE_ENV_VAR, "")
    if raw == _env_mode_cache[0]:
        return _env_mode_cache[1]
    val = raw.strip().lower()
    if val in ("", "0", "off"):
        mode = MODE_OFF
    elif val in ("1", "on", "quarantine"):
        mode = MODE_QUARANTINE
    elif val == "error":
        mode = MODE_ERROR
    else:
        raise TorchMetricsUserError(
            f"{QUARANTINE_ENV_VAR}={val!r} is not a recognized quarantine mode "
            "(expected unset/'0'/'off', '1'/'on'/'quarantine', or 'error')"
        )
    _env_mode_cache = (raw, mode)
    return mode


def quarantine_enabled() -> bool:
    """Whether compiled/eager updates apply the in-graph quarantine transaction."""
    return quarantine_mode() == MODE_QUARANTINE


def quarantine_error() -> bool:
    """Whether admission failures raise (fail-loud mode) instead of quarantining."""
    return quarantine_mode() == MODE_ERROR


def set_quarantine_mode(value: Optional[Any]) -> None:
    """Force the mode process-wide; ``None`` restores env resolution.

    Accepts ``True``/``"1"`` (quarantine), ``False``/``"0"`` (off), ``"error"``.
    """
    global _mode_override
    _mode_override = _coerce_mode(value)


def _coerce_mode(value: Optional[Any]) -> Optional[str]:
    if value is None:
        return None
    if value is True:
        return MODE_QUARANTINE
    if value is False:
        return MODE_OFF
    mode = str(value).strip().lower()
    if mode in (MODE_OFF, MODE_QUARANTINE, MODE_ERROR):
        return mode
    raise ValueError(f"quarantine mode must be one of '0', '1', 'error' (got {value!r})")


@contextmanager
def quarantine_context(mode: Any = True) -> Generator[None, None, None]:
    """Scoped quarantine mode (tests, benches). Toggling mid-stream retraces
    the affected signatures once (the counter rider is a ``treedef-change``)."""
    global _mode_override
    prev = _mode_override
    _mode_override = _coerce_mode(mode)
    try:
        yield
    finally:
        _mode_override = prev


# ------------------------------------------------------------------ admission


def _input_bounds(metric: Any) -> Optional[int]:
    """Integer label bound for range checks, when the metric declares one."""
    bound = getattr(metric, "num_classes", None)
    if isinstance(bound, bool) or not isinstance(bound, (int, np.integer)):
        return None
    return int(bound) if int(bound) > 0 else None


def build_admission(metric: Any, inputs: Sequence[Any]) -> Callable[[Sequence[Any]], Any]:
    """Jittable per-batch admission check, planned once per compile signature.

    The plan is static (which input positions get which check, from the
    example dtypes); the returned callable lowers into the caller's graph:
    float/complex inputs contribute ``~isfinite(x).all()``, integer inputs of
    a ``num_classes``-declaring metric contribute ``(x < 0) | (x >= bound)``.
    Zero pad rows (``engine/bucketing.py``) are finite and in-range by
    construction, so padding can never read as poison. Always returns a
    callable — with nothing checkable the flag is a constant False that XLA
    folds away.
    """
    checks: List[Tuple[int, str, Optional[int]]] = []
    bound = _input_bounds(metric)
    for i, a in enumerate(inputs):
        dtype = getattr(a, "dtype", None)
        if dtype is None:
            continue
        kind = np.dtype(dtype).kind
        if kind in "fc":
            checks.append((i, "finite", None))
        elif kind in "iu" and bound is not None:
            checks.append((i, "range", bound))

    def admission(flat: Sequence[Any]) -> Any:
        import jax.numpy as jnp

        poisoned = jnp.asarray(False)
        for i, check, b in checks:
            x = flat[i]
            if check == "finite":
                poisoned = poisoned | ~jnp.isfinite(x).all()
            else:
                poisoned = poisoned | (x < 0).any() | (x >= b).any()
        return poisoned

    return admission


def transact(metric: Any, old: Dict[str, Any], new: Dict[str, Any], poisoned: Any) -> Dict[str, Any]:
    """The in-graph state transaction (jittable, runs inside the compiled step).

    Every non-rider state leaf is selected against its pre-update value via
    ``jnp.where(poisoned, old, new)`` — including the compensation residual
    dict (``engine/numerics.py``), whose entries roll back leaf-wise so a
    quarantined batch leaves (value, residual) pairs bit-exact; the
    ``__quarantine__`` counter increments by the flag; with the sentinel rider
    present, its health checks fold over the SELECTED (final) states — a
    quarantined batch therefore raises only the ``input_poisoned`` bit while
    ``nan``/``inf`` stay clear, because the state genuinely stays clean.
    """
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.diag import sentinel as _sentinel

    from torchmetrics_tpu.engine import statespec as _statespec

    out: Dict[str, Any] = {}
    selected: Dict[str, Any] = {}
    # rollback selection over every STATE leaf: rider roles with their own
    # fold-forward semantics (the quarantine counter increments, the sentinel
    # folds over the selected states below) are exempt; the compensation
    # residual is NOT — it rolls back leaf-wise with its value so a
    # quarantined batch leaves (value, residual) pairs bit-exact
    rollback_exempt = _statespec.RIDER_KEYS - {_statespec.COMPENSATION_KEY}
    for k, v in new.items():
        if k in rollback_exempt:
            continue
        sel = jax.tree_util.tree_map(lambda o, n: jnp.where(poisoned, o, n), old[k], v)
        out[k] = sel
        selected[k] = sel
    if STATE_KEY in new:
        out[STATE_KEY] = old[STATE_KEY] + poisoned.astype(old[STATE_KEY].dtype)
    if _sentinel.STATE_KEY in new:
        flags = _sentinel.update_flags(new[_sentinel.STATE_KEY], selected, metric)
        out[_sentinel.STATE_KEY] = flags | jnp.where(
            poisoned, jnp.int32(_sentinel.FLAG_INPUT_POISONED), jnp.int32(0)
        )
    return out


# ------------------------------------------------------------------ eager parity


def _flat_inputs(args: Sequence[Any], kwargs: Dict[str, Any]) -> List[Any]:
    return list(args) + [kwargs[k] for k in sorted(kwargs)]


def admission_check_or_raise(metric: Any, args: Sequence[Any], kwargs: Dict[str, Any]) -> None:
    """``=error`` mode: host-side admission check BEFORE any state mutation.

    Fail-loud mode trades one sanctioned device sync per step for an
    immediate, typed :class:`QuarantinedBatchError` — the explicit opposite
    of the zero-transfer quarantine path, applied identically on the
    compiled, fused, and eager routes (the check runs before dispatch).
    """
    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    inputs = _flat_inputs(args, kwargs)
    poisoned = build_admission(metric, inputs)(inputs)
    with transfer_allowed("quarantine-check"):
        bad = bool(np.asarray(poisoned))
    if bad:
        _diag.record("update.quarantine", type(metric).__name__, mode="error")
        raise QuarantinedBatchError(
            f"batch failed admission for {type(metric).__name__}: a float input is"
            " non-finite or an integer label is out of [0, num_classes)."
            " TORCHMETRICS_TPU_QUARANTINE=error raises instead of quarantining;"
            " use mode '1' to skip poisoned batches in-graph instead."
        )


def eager_update(metric: Any, run_update: Callable[[], None], args: Sequence[Any], kwargs: Dict[str, Any]) -> None:
    """Quarantine-guarded eager update — the engine-off parity path.

    Fixed-shape array states get the same zero-transfer treatment as the
    compiled path (in-graph ``where`` select + counter increment). A state
    whose shape/dtype/structure changed under the update (list appends, the
    x64 first-step promotion) cannot be selected in-graph — the flag is read
    at the sanctioned ``quarantine-check`` boundary and the pre-update refs
    are restored wholesale on poison.
    """
    import jax.numpy as jnp

    from torchmetrics_tpu.diag import sentinel as _sentinel
    from torchmetrics_tpu.engine import numerics as _numerics

    inputs = _flat_inputs(args, kwargs)
    admission = build_admission(metric, inputs)
    old: Dict[str, Any] = {}
    for k in metric._defaults:
        v = getattr(metric, k)
        old[k] = list(v) if isinstance(v, list) else v
    # the compensation residual rolls back with the states: a quarantined
    # batch must leave (value, residual) pairs bit-exact. Absent-before reads
    # as zeros — exactly the residual a pre-update metric carries.
    had_res = _numerics.ATTR in metric.__dict__
    old_res = dict(metric.__dict__.get(_numerics.ATTR) or {})
    poisoned = admission(inputs)
    run_update()

    selectable = True
    for k, o in old.items():
        new = getattr(metric, k)
        if isinstance(o, list) or isinstance(new, list):
            selectable = False
            break
        if (
            getattr(new, "shape", None) is None
            or getattr(o, "shape", None) is None
            or tuple(new.shape) != tuple(o.shape)
            or new.dtype != o.dtype
        ):
            selectable = False
            break

    count = ensure_count(metric)
    if selectable:
        for k, o in old.items():
            setattr(metric, k, jnp.where(poisoned, o, getattr(metric, k)))
        new_res = metric.__dict__.get(_numerics.ATTR)
        if new_res is not None:
            setattr(
                metric,
                _numerics.ATTR,
                {
                    k: jnp.where(poisoned, old_res.get(k, jnp.zeros_like(v)), v)
                    for k, v in new_res.items()
                },
            )
        setattr(metric, ATTR, count + poisoned.astype(count.dtype))
        if _sentinel.sentinel_enabled():
            flags = _sentinel.ensure_flags(metric)
            setattr(
                metric, _sentinel.ATTR,
                flags | jnp.where(poisoned, jnp.int32(_sentinel.FLAG_INPUT_POISONED), jnp.int32(0)),
            )
        return

    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    with transfer_allowed("quarantine-check"):
        bad = bool(np.asarray(poisoned))
    if bad:
        for k, o in old.items():
            setattr(metric, k, o)
        if had_res:
            setattr(metric, _numerics.ATTR, old_res)
        elif _numerics.ATTR in metric.__dict__:
            del metric.__dict__[_numerics.ATTR]
        setattr(metric, ATTR, count + jnp.asarray(1, count.dtype))
        if _sentinel.sentinel_enabled():
            flags = _sentinel.ensure_flags(metric)
            setattr(metric, _sentinel.ATTR, flags | jnp.int32(_sentinel.FLAG_INPUT_POISONED))
        _diag.record("update.quarantine", type(metric).__name__, count=1, path="eager")


def eager_apply(metric: Any, args: Sequence[Any], kwargs: Dict[str, Any]) -> None:
    """Run a raw update with quarantine parity — the ladder's eager rung.

    The fallback ladder applies this to a residual chunk the compiled path
    could not take, so an OOM-demoted chunk still honors the admission
    contract instead of sneaking poison past it.
    """
    if quarantine_enabled():
        eager_update(metric, lambda: metric._raw_update(*args, **kwargs), args, kwargs)
    else:
        metric._raw_update(*args, **kwargs)


# ------------------------------------------------------------------ fallback ladder


#: consecutive classified compile failures of ONE signature before it is
#: demoted like a structural failure — a PERSISTENT resource failure must not
#: pay a full XLA compile attempt on every step forever
TRANSIENT_RETRY_BUDGET = 3


def transient_budget_exhausted(counts: Dict[Any, int], key: Any) -> bool:
    """Count one classified failure for ``key``; True once the budget is spent.

    The engines keep ``counts`` per cache: transient failures under the budget
    leave the signature retryable (the next step may find memory freed), the
    budget-exhausting one demotes it permanently — bounded recompile cost,
    bounded event spam.
    """
    n = counts.get(key, 0) + 1
    counts[key] = n
    return n >= TRANSIENT_RETRY_BUDGET


def classify_and_demote(
    cache: Dict[Any, Any], fallback: Any, counts: Dict[Any, int], key: Any, exc: BaseException
) -> Optional[str]:
    """The single first-dispatch-failure policy shared by every engine cache.

    Structural trace failures (:func:`classify_dispatch_error` -> None) demote
    ``key`` to ``fallback`` permanently; classified transient failures leave it
    retryable until :data:`TRANSIENT_RETRY_BUDGET` of them demote it anyway,
    suffixing the classification with ``-budget``. Returns the (possibly
    suffixed) classification, or None for a structural failure.
    """
    classified = classify_dispatch_error(exc)
    if classified is None:
        cache[key] = fallback
    elif transient_budget_exhausted(counts, key):
        cache[key] = fallback
        classified = f"{classified}-budget"
    return classified


def classify_dispatch_error(exc: BaseException) -> Optional[str]:
    """Classify a compile/dispatch failure as transient-resource vs structural.

    Returns ``"resource-exhausted"`` (OOM-family), ``"xla-runtime"`` (other
    backend runtime failures), or ``None`` for structural trace failures
    (untraceable update bodies) — only the latter permanently demote a
    signature to eager; classified failures step down the ladder and may
    retry on a later step.
    """
    name = type(exc).__name__
    text = f"{name}: {exc}".lower()
    if "resource_exhausted" in text or "resource exhausted" in text or "out of memory" in text or name == "MemoryError":
        return "resource-exhausted"
    if name == "XlaRuntimeError":
        return "xla-runtime"
    return None


# ------------------------------------------------------------------ counter surfacing


def ensure_count(metric: Any) -> Any:
    """The metric's device quarantine counter, created (zero) on first use.

    Accumulates in :func:`~torchmetrics_tpu.engine.numerics.count_dtype` —
    int64 under the x64 flag, int32 otherwise — resolved at creation so the
    dtype never flips mid-stream (overflow-safe widening, ISSUE 8).
    """
    val = getattr(metric, ATTR, None)
    if val is None:
        import jax.numpy as jnp

        from torchmetrics_tpu.engine import numerics as _numerics

        val = jnp.zeros((), _numerics.count_dtype())
        setattr(metric, ATTR, val)
        metric._quarantine_reported = 0
    _REGISTRY[id(metric)] = metric
    return val


def _stats_for(metric: Any):
    """The EngineStats block quarantine deltas attribute to."""
    eng = getattr(metric, "_engine", None)
    if eng is not None:
        return eng.stats
    epoch = getattr(metric, "_epoch", None)
    if epoch is not None:
        return epoch.stats
    st = metric.__dict__.get("_txn_stats")
    if st is None:
        from torchmetrics_tpu.engine.stats import EngineStats

        st = EngineStats("txn:" + type(metric).__name__)
        metric._txn_stats = st
    return st


def read_quarantine(metric: Any) -> Dict[str, Any]:
    """Epoch-end host readout of the quarantine counter — the SANCTIONED boundary.

    Returns ``{"owner", "count"}``. The device→host read runs inside
    ``transfer_allowed("quarantine-read")`` so a strict-guarded epoch stays
    clean; any growth since the last read lands in
    ``EngineStats.quarantined_batches`` and one ``update.quarantine`` event
    (the hot loop itself never reads the flag — events surface here, at the
    declared boundary, by design). Read on unsynced state for this rank's
    count, or inside a sync window for the world total.
    """
    val = getattr(metric, ATTR, None)
    if val is None:
        return {"owner": type(metric).__name__, "count": 0}
    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    with transfer_allowed("quarantine-read"):
        total = int(np.asarray(val))
    reported = int(getattr(metric, "_quarantine_reported", 0))
    if total > reported:
        st = _stats_for(metric)
        st.quarantined_batches += total - reported
        _diag.record("update.quarantine", type(metric).__name__, count=total - reported, total=total)
        # provenance: quarantined batches were skipped in-graph — the value
        # an observer reads does NOT cover them
        _lineage.note_excluded(type(metric).__name__, "quarantined", total - reported)
    if total != reported:
        metric._quarantine_reported = total
    return {"owner": type(metric).__name__, "count": total}


def mark_reported(metric: Any) -> None:
    """Align the reported watermark with the LIVE counter, surfacing nothing.

    ``unsync`` calls this when a sanctioned read happened inside the sync
    window: that read surfaced the WORLD total (which already contains this
    rank's local count), so after the local counter is restored the watermark
    must equal it — restoring the pre-sync watermark instead would re-open the
    local share as an unreported delta and double-count it at the next read.
    """
    val = getattr(metric, ATTR, None)
    if val is None:
        return
    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    with transfer_allowed("quarantine-read"):
        metric._quarantine_reported = int(np.asarray(val))


def quarantine_report() -> List[Dict[str, Any]]:
    """Sanctioned readout of every registered counter, aggregated per owner.

    Same shape discipline as ``sentinel_report``: one row per owner class
    (counts summed, instances counted), flagged owners first, deterministic —
    repeated exports of the same state are byte-identical.
    """
    by_owner: Dict[str, Dict[str, Any]] = {}
    for metric in list(_REGISTRY.values()):
        row = read_quarantine(metric)
        slot = by_owner.setdefault(row["owner"], {"owner": row["owner"], "count": 0, "instances": 0})
        slot["count"] += row["count"]
        slot["instances"] += 1
    rows = sorted(by_owner.values(), key=lambda r: (r["count"] == 0, r["owner"]))
    return rows


def reset_quarantine() -> None:
    """Zero every registered counter and clear the registry
    (``reset_engine_stats`` lockstep)."""
    import jax.numpy as jnp

    for metric in list(_REGISTRY.values()):
        val = getattr(metric, ATTR, None)
        if val is not None:
            setattr(metric, ATTR, jnp.zeros_like(val))  # dtype-preserving (x64 widening)
            metric._quarantine_reported = 0
    _REGISTRY.clear()
