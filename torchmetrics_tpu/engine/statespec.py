"""First-class declarative state specifications — the single source of role truth.

Role knowledge used to be smeared across string prefixes and attribute
conventions: ``parallel/packing.py`` re-derived fold kinds from
``dist_reduce_fx`` identities and read ``_hh_fold_info`` for the heavy-hitter
pair, ``engine/numerics.py`` gated compensation on ``_engine_state_additive``/
``_engine_row_additive`` class flags, ``engine/bucketing.py`` re-checked the
same flags, the pad-subtract identity matched reserved pytree key strings, the
divergence audit read ``_rank_invariant_states``, and ``serve/`` invented
``hh-ids``/``hh-counts``/ring-clock roles no other layer could see. Five
subsystems each re-parsing conventions is exactly the surface a sharding layer
(ROADMAP item 1) cannot be built on.

This module makes the role a first-class, declarative :class:`StateSpec`,
registered at ``Metric.add_state`` time and consumed by every engine:

- **fold semantics** (``sum``/``mean``/``max``/``min``/``cat``/``none``/
  ``custom``) — what the packed sync, ``merge_state``, and the reshard split
  algebra do with the state;
- **role** — plain ``state``, or one of the structured roles: the
  heavy-hitter ``hh-grid``/``hh-ids``/``hh-counts`` joint fold
  (``serve/sketch.py``), the max-reduced ``ring-clock`` (``serve/window.py``),
  and the reserved rider roles (``sentinel``/``quarantine``/
  ``comp-residual``) that ride compiled-step pytrees under
  :data:`RIDER_KEYS`;
- **dtype policy** — ``"count"`` marks states under the PR-8
  ``count_dtype()`` widening contract (int64 under x64, resolved at creation);
- **additivity** — ``row_additive`` (the pad-subtract identity holds per
  batch row; bucketing eligibility) and ``state_additive``
  (``new = old + g(batch)``; compensation eligibility);
- **pad exemption** — rider states the bucketing pad-subtract must pass
  through untouched;
- **rank invariance** — values must be identical on every rank (the packed
  sync's divergence audit fingerprints these);
- **shard rule** — the SPMD sharded-state engine's placement input
  (``parallel/sharding.py``): a named entry in :data:`SHARD_RULES` that
  :func:`resolve_shard_rule` resolves to the live ``NamedSharding`` on the
  active state mesh (``None`` = replicated). ``"class_axis"`` /
  ``"row_sharded"`` partition the leading dim over the ``"state"`` axis so
  million-class states are born distributed at ``add_state``; with no mesh
  active every rule degrades to replication — today's semantics, free.

Consumers resolve specs through :func:`spec_of`. Metrics that registered
their states through ``add_state`` always hit the registry; anything else
(out-of-tree metrics hand-rolling ``_defaults``/``_reductions``, pre-spec
pickles) falls back to a DERIVED spec built from the deprecated attribute
conventions — counted once per (metric, state) in
``EngineStats.spec_fallbacks``, recorded as a ``spec.fallback`` flight-
recorder event, and exported as ``tm_tpu_spec_fallbacks_total`` so migrating
out-of-tree metrics are discoverable from a scrape. The in-tree suite runs at
zero fallbacks.

On top of the registry sits **cross-metric common-subexpression fusion**
(CSE): metrics whose *state-producing reduction* is provably identical — the
stat-scores family's TP/FP/TN/FN update with matching task/num_classes/
``top_k``/``ignore_index`` knobs, confusion matrices with matching shape knobs
— declare a :func:`reduction_signature`, and ``MetricCollection`` merges them
into one compute group AT CONSTRUCTION TIME: the shared reduction traces
once, N metrics derive their computes from one canonical donated state
(``collections.py``). ``TORCHMETRICS_TPU_CSE=0`` opts out (falls back to the
legacy first-step value-equality discovery); unrecognized values fail loud
per the PR-7 env contract.
"""

from __future__ import annotations

import dataclasses
import os
from contextlib import contextmanager
from typing import Any, Callable, Dict, Generator, Optional, Tuple

from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.engine.stats import EngineStats
from torchmetrics_tpu.utilities.data import (
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)

__all__ = [
    "CSE_ENV_VAR",
    "COMPENSATION_KEY",
    "PAD_EXEMPT_KEYS",
    "QUARANTINE_KEY",
    "RIDER_KEYS",
    "SENTINEL_KEY",
    "SHARD_RULES",
    "StateSpec",
    "cse_context",
    "cse_enabled",
    "fold_name",
    "reduction_signature",
    "register_state_spec",
    "resolve_shard_rule",
    "set_cse",
    "spec_fallback_count",
    "spec_of",
    "specs_of",
]

CSE_ENV_VAR = "TORCHMETRICS_TPU_CSE"

#: the reserved pytree keys rider roles ride under inside compiled steps.
#: These are the canonical definitions; ``diag/sentinel.py``,
#: ``engine/txn.py`` and ``engine/numerics.py`` keep their local ``STATE_KEY``
#: aliases for their own machinery and a test pins the two in lockstep.
SENTINEL_KEY = "__sentinel__"
QUARANTINE_KEY = "__quarantine__"
COMPENSATION_KEY = "__compensation__"

#: every rider key — the transactional rollback and the scan carry treat these
#: as non-state leaves with role-specific handling
RIDER_KEYS = frozenset({SENTINEL_KEY, QUARANTINE_KEY, COMPENSATION_KEY})

#: rider keys the bucketing pad-subtract identity must pass through untouched:
#: pad rows cannot raise health flags, poison a batch, or carry rounding error
PAD_EXEMPT_KEYS = RIDER_KEYS

def _rule_replicate(spec: "StateSpec", value: Any = None) -> None:
    """State lives whole on every device — no placement constraint."""
    return None


def _rule_dim0(spec: "StateSpec", value: Any = None) -> Optional[Any]:
    """Partition the leading dim over the ``"state"`` mesh axis (or replicate)."""
    from torchmetrics_tpu.parallel import sharding as _sharding

    return _sharding.partition_dim0(spec, value)


#: named shard rules, resolved by the SPMD sharded-state engine
#: (``parallel/sharding.py``). ``replicate`` is the default: state lives whole
#: on every device. ``class_axis`` partitions a per-class state's leading dim
#: (per-class TP/FP/TN/FN counters, confusion-matrix rows, the multilabel
#: ``(num_labels, 2, 2)`` stack) over the ``"state"`` mesh axis so a
#: million-class state holds ~1/N per device; ``row_sharded`` is the same
#: dim-0 partition for generic row-major matrix states (feature-covariance
#: accumulators, embedding tables) where the rows carry no per-class
#: semantics. Both degrade to replication — recorded, never silent — when no
#: mesh is active or the leading dim is not divisible by the mesh axis.
SHARD_RULES: Dict[str, Callable[["StateSpec", Any], Optional[Any]]] = {
    "replicate": _rule_replicate,
    "class_axis": _rule_dim0,
    "row_sharded": _rule_dim0,
}

_FOLD_BY_FN = {
    dim_zero_sum: "sum",
    dim_zero_mean: "mean",
    dim_zero_max: "max",
    dim_zero_min: "min",
    dim_zero_cat: "cat",
}

#: the attribute the per-metric spec registry lives under
REGISTRY_ATTR = "_state_specs"

# module-level stats block: spec fallbacks are a process-wide migration
# signal, not a per-engine property — one EngineStats joins the weak registry
# so engine_report()/telemetry aggregate it like any other counter (the module
# global keeps it alive; the registry only holds it weakly)
_STATS = EngineStats("statespec")

_cse_override: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class StateSpec:
    """Declarative specification of one registered metric state.

    Immutable and picklable (``fold_fn`` custom folds must be module-level
    callables, which ``dist_reduce_fx`` already required for checkpointing).

    Attributes:
        name: the state attribute name.
        fold: cross-rank / cross-shard fold semantic — ``"sum"``, ``"mean"``,
            ``"max"``, ``"min"``, ``"cat"``, ``"none"`` (raw stack), or
            ``"custom"`` (``fold_fn`` applies).
        fold_fn: the callable for ``fold == "custom"``.
        role: ``"state"`` for plain states, or a structured role:
            ``"hh-grid"``/``"hh-ids"``/``"hh-counts"`` (the joint heavy-hitter
            fold — ``hh`` carries ``(grid_attr, k, depth, width)`` on the ids
            spec), ``"ring-clock"`` (max-reduced window cursor). The rider
            roles (``"sentinel"``/``"quarantine"``/``"comp-residual"``) are
            reserved for the pytree riders and never registered via
            ``add_state``.
        dtype_policy: ``"default"``, or ``"count"`` for states under the
            ``count_dtype()`` widening contract (int64 under x64, resolved at
            creation — PR 8).
        row_additive: the pad-subtract identity holds per batch row
            (``engine/bucketing.py`` eligibility; derived from the metric's
            ``_engine_row_additive`` declaration at registration).
        state_additive: ``new = old + g(batch)`` — the zero-state trick of the
            compensated two-sum is exact (``engine/numerics.py`` eligibility).
        pad_exempt: the bucketing pad-subtract passes this leaf through
            untouched (rider semantics).
        rank_invariant: values must be identical on every rank; the packed
            sync's divergence audit fingerprints these.
        hh: ``hh-ids`` only — ``(grid_attr, k, depth, width)`` tying the top-k
            pair to its count-min grid for the joint packed fold.
        shard_rule: named entry in :data:`SHARD_RULES` — ``"replicate"`` (the
            default), or ``"class_axis"``/``"row_sharded"`` to partition the
            leading dim over the active state mesh (``parallel/sharding.py``);
            derived from the metric's class-level ``_engine_shard_rules``
            declaration at registration.
    """

    name: str
    fold: str = "none"
    fold_fn: Optional[Callable] = None
    role: str = "state"
    dtype_policy: str = "default"
    row_additive: bool = False
    state_additive: bool = False
    pad_exempt: bool = False
    rank_invariant: bool = False
    hh: Optional[Tuple[str, int, int, int]] = None
    shard_rule: str = "replicate"


def fold_name(dist_reduce_fx: Any) -> Tuple[str, Optional[Callable]]:
    """Canonical ``(fold, fold_fn)`` for a resolved ``dist_reduce_fx`` value."""
    name = _FOLD_BY_FN.get(dist_reduce_fx)
    if name is not None:
        return name, None
    if dist_reduce_fx is None:
        return "none", None
    if callable(dist_reduce_fx):
        return "custom", dist_reduce_fx
    raise ValueError(f"unresolvable dist_reduce_fx {dist_reduce_fx!r}")


def resolve_shard_rule(spec: StateSpec, value: Any = None, owner: str = "") -> Optional[Any]:
    """Resolve a spec's shard rule to its live sharding (``None`` = replicate).

    Returns the ``jax.sharding.NamedSharding`` the rule places ``value``
    under on the active state mesh (``parallel/sharding.py``), or ``None``
    when the state is replicated — because the rule is ``"replicate"``, no
    mesh is active, or the rule degraded (indivisible leading dim, recorded
    as a ``shard.fallback`` event and counted in ``shard_degrades``).
    ``value`` carries the shape the partitioning inspects; rules other than
    ``"replicate"`` resolve to ``None`` without it. Unknown rule names raise,
    listing the registered rules — a typo must not silently replicate a state
    the operator believes is sharded.

    The per-state-name partition-rule table
    (:func:`~torchmetrics_tpu.parallel.sharding.set_partition_rules`) is
    consulted FIRST: an entry matching ``owner/name`` (``owner`` is the
    metric class name when the caller knows it) overrides the named rule with
    its explicit per-dim ``PartitionSpec`` — the operator-side channel for
    sharding states whose class declarations can't be edited.
    """
    try:
        rule = SHARD_RULES[spec.shard_rule]
    except KeyError:
        raise ValueError(
            f"state {spec.name!r} names unknown shard rule {spec.shard_rule!r}"
            f" (registered rules: {sorted(SHARD_RULES)})"
        ) from None
    from torchmetrics_tpu.parallel import sharding as _sharding

    match = _sharding.match_partition_rule(spec.name, owner)
    if match is not None:
        return _sharding.apply_partition_rule(spec, value, match[1])
    return rule(spec, value)


# ------------------------------------------------------------------ registry


def build_spec(
    metric: Any,
    name: str,
    dist_reduce_fx: Any,
    overrides: Optional[Any] = None,
) -> StateSpec:
    """The spec ``add_state`` registers: derived defaults + explicit overrides.

    Derivation reads the metric's class-level declarations ONCE, at
    registration — ``_engine_row_additive``/``_engine_state_additive`` for the
    additivity flags and ``_rank_invariant_states`` for audit membership — so
    the registered spec is a pure function of the metric definition (the
    packed-sync layout-symmetry rule). ``overrides`` is a ready
    :class:`StateSpec` or a dict of field overrides (the ``serve/`` roles).
    """
    if isinstance(overrides, StateSpec):
        return dataclasses.replace(overrides, name=name)
    fold, fold_fn = fold_name(dist_reduce_fx)
    fields: Dict[str, Any] = {
        "name": name,
        "fold": fold,
        "fold_fn": fold_fn,
        "row_additive": bool(getattr(metric, "_engine_row_additive", False)),
        "state_additive": bool(getattr(metric, "_engine_state_additive", False)),
        "rank_invariant": name in (getattr(metric, "_rank_invariant_states", ()) or ()),
        # SPMD placement (parallel/sharding.py): the class declares per-state
        # rules once (``_engine_shard_rules = {"tp": "class_axis", ...}``);
        # with no active mesh every rule resolves to replication, so the
        # declaration is free until an operator turns the mesh on
        "shard_rule": (getattr(metric, "_engine_shard_rules", None) or {}).get(name, "replicate"),
    }
    if overrides:
        unknown = set(overrides) - {f.name for f in dataclasses.fields(StateSpec)}
        if unknown:
            raise ValueError(f"unknown StateSpec field(s) for state {name!r}: {sorted(unknown)}")
        if "name" in overrides and overrides["name"] != name:
            # a renamed spec would register under the wrong key: spec_of would
            # miss, silently drop the declared role, and count a spurious
            # fallback — the spec's name IS the state's name, always
            raise ValueError(
                f"StateSpec override for state {name!r} must not rename it"
                f" (got name={overrides['name']!r})"
            )
        fields.update(overrides)
        fields["name"] = name
    if fields["shard_rule"] not in SHARD_RULES:
        # validated at REGISTRATION, not first resolution: a typo'd rule on a
        # state the mesh never touches would otherwise sit latent until the
        # first sharded run of a completely different workload
        raise ValueError(
            f"state {name!r} names unknown shard rule {fields['shard_rule']!r}"
            f" (registered rules: {sorted(SHARD_RULES)})"
        )
    return StateSpec(**fields)


def register_state_spec(metric: Any, spec: StateSpec) -> StateSpec:
    """Install ``spec`` in the metric's registry (``add_state`` calls this)."""
    registry = metric.__dict__.get(REGISTRY_ATTR)
    if registry is None:
        registry = {}
        object.__setattr__(metric, REGISTRY_ATTR, registry)
    registry[spec.name] = spec
    return spec


def _derive_legacy(metric: Any, name: str) -> StateSpec:
    """Spec derivation from the deprecated attribute/prefix conventions.

    The counted fallback path: out-of-tree metrics that hand-roll
    ``_defaults``/``_reductions`` (or pre-spec pickles) resolve here until
    they migrate to ``add_state``/``register_state_spec``. Mirrors exactly
    what the consumers used to re-derive for themselves — including the
    ``_hh_fold_info`` heavy-hitter declaration.
    """
    red = getattr(metric, "_reductions", {}).get(name)
    spec = build_spec(metric, name, red)
    hh_info = getattr(metric, "_hh_fold_info", None)
    if hh_info is not None:
        if name == hh_info.get("cms"):
            spec = dataclasses.replace(spec, role="hh-grid")
        elif name == hh_info.get("ids"):
            spec = dataclasses.replace(
                spec,
                role="hh-ids",
                hh=(
                    hh_info["cms"], int(hh_info["k"]),
                    int(hh_info["depth"]), int(hh_info["width"]),
                ),
            )
        elif name == hh_info.get("counts"):
            spec = dataclasses.replace(spec, role="hh-counts")
    return spec


def spec_of(metric: Any, name: str, consumer: str = "") -> StateSpec:
    """The registered :class:`StateSpec` for ``metric.<name>``.

    Registry miss = the deprecated fallback: the spec is derived from the
    legacy attribute conventions, CACHED back into the registry (so the
    derivation and its telemetry fire once per (metric, state), never per
    step), counted in ``EngineStats.spec_fallbacks``, and recorded as a
    ``spec.fallback`` event naming the consumer that had to fall back.
    """
    registry = metric.__dict__.get(REGISTRY_ATTR)
    if registry is not None:
        spec = registry.get(name)
        if spec is not None:
            return spec
    spec = _derive_legacy(metric, name)
    register_state_spec(metric, spec)
    _STATS.spec_fallbacks += 1
    _diag.record(
        "spec.fallback", type(metric).__name__, state=name, consumer=consumer,
        role=spec.role, fold=spec.fold,
    )
    return spec


def specs_of(metric: Any, consumer: str = "") -> Dict[str, StateSpec]:
    """Every registered state's spec, in ``_reductions`` registration order."""
    return {
        name: spec_of(metric, name, consumer)
        for name in getattr(metric, "_reductions", {})
    }


def spec_fallback_count() -> int:
    """Process-wide count of deprecated-convention spec derivations."""
    return _STATS.spec_fallbacks


# ------------------------------------------------------------------ CSE policy


def cse_enabled() -> bool:
    """Whether signature-based cross-metric fusion drives group discovery.

    ``TORCHMETRICS_TPU_CSE=0|off`` reverts ``MetricCollection`` to the legacy
    first-step value-equality discovery; unrecognized values fail loud (the
    PR-7 env contract — a typo must not silently change fusion semantics).
    """
    if _cse_override is not None:
        return _cse_override
    raw = os.environ.get(CSE_ENV_VAR, "").strip().lower()
    if raw in ("", "1", "on"):
        return True
    if raw in ("0", "off"):
        return False
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    raise TorchMetricsUserError(
        f"{CSE_ENV_VAR} must be '0'/'off' or '1'/'on' (got {raw!r})"
    )


def set_cse(value: Optional[bool]) -> None:
    """Force CSE discovery on/off process-wide; ``None`` restores env/default."""
    global _cse_override
    _cse_override = value


@contextmanager
def cse_context(enabled: bool = True) -> Generator[None, None, None]:
    """Scoped CSE enablement (tests, benches). Affects GROUP DISCOVERY, which
    runs at collection construction / first step — toggling does not regroup
    an already-discovered collection."""
    global _cse_override
    prev = _cse_override
    _cse_override = enabled
    try:
        yield
    finally:
        _cse_override = prev


def update_family(metric: Any) -> Tuple[str, str]:
    """Identity of a metric's state-producing update body for CSE signatures.

    Keyed on the CLASS'S actual ``update`` function (module + qualname): the
    derivative metrics that inherit a task base's update verbatim — accuracy,
    precision, recall, F-beta, specificity, hamming over stat-scores; kappa,
    jaccard, matthews over confusion matrices — share a family, while any
    subclass that overrides ``update`` breaks signature equality
    automatically, with no declaration to forget. The ONE keying rule for
    every declaring family (stat-scores and confusion-matrix bases both
    delegate here).
    """
    fn = type(metric).update
    return (fn.__module__, fn.__qualname__)


def reduction_signature(metric: Any) -> Optional[Tuple]:
    """The metric's state-producing-reduction signature, or ``None``.

    Two metrics with EQUAL signatures are guaranteed (by the declaring class)
    to run byte-identical ``update`` bodies onto identically-shaped,
    identically-named states — the proof obligation the legacy discovery
    established empirically by running one eager step per member and
    value-comparing states on the host. A signature is a pure function of the
    metric definition (class + constructor knobs that reach the update), so
    discovery happens at collection CONSTRUCTION: the first step is already
    fused, and two metrics whose knobs differ can never be merged by a
    first-batch value coincidence (e.g. differing ``ignore_index`` with no
    ignored labels in batch 1 — a latent mis-merge of the value-based path).

    ``None`` (the base default) means "no declaration": the metric falls back
    to the legacy value-equality discovery.
    """
    fn = getattr(metric, "_cse_signature", None)
    if fn is None:
        return None
    sig = fn()
    if sig is None:
        return None
    # the class vouches for update-body identity; the registered state layout
    # (names in order) joins the key so a subclass that adds a state can never
    # silently collide with its parent's signature
    return (*sig, tuple(getattr(metric, "_reductions", {})))
