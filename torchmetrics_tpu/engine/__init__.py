"""Fused update engine — cached XLA executables for the metric hot loop.

The north star demands ``update()``/``compute()`` lowering to single XLA graphs
with zero host transfers in the hot loop. The eager path re-enters Python per
``update`` and pays one dispatch per ``jnp`` op per metric per step; at scale the
dispatch floor — not the kernels — dominates (BENCH_r04: 6.2 ms dispatch floor vs
1.7 ms collective marginal at 128 chips). This subsystem removes that floor:

- :class:`~torchmetrics_tpu.engine.compiled.CompiledUpdate` — per-metric
  compiled-step cache. A metric's ``update`` is traced ONCE per
  ``(state treedef, input shapes/dtypes)`` signature into a ``jax.jit``
  executable with the state pytree donated (``donate_argnums=(0,)``), so a
  steady-state step is a single cached dispatch with no re-trace and no state
  copy.
- :mod:`~torchmetrics_tpu.engine.bucketing` — shape buckets for ragged final
  batches. Inputs pad up to the next power-of-two bucket and a traced
  ``n_pad`` scalar subtracts the pad rows' (constant) contribution in-graph,
  bounding compiled variants at O(log max_batch) instead of one per odd size.
- :class:`~torchmetrics_tpu.engine.fusion.FusedUpdate` — collection-level
  dispatch fusion: the update bodies of every compute-group leader in a
  ``MetricCollection`` trace into ONE executable, so an N-metric step costs one
  dispatch instead of N.
- :mod:`~torchmetrics_tpu.engine.async_dispatch` — double-buffered background
  drains over the scan queues: ``update()`` becomes a pure enqueue, a bounded
  worker launches the same cached donated scan executable while the caller
  fills the next buffer, and every state observation JOINS the in-flight work
  before reading (``async_context`` / ``TORCHMETRICS_TPU_ASYNC``).
- :mod:`~torchmetrics_tpu.engine.stats` — per-engine counters (traces, cache
  hits, fallbacks, donation copies, bytes moved, retrace causes) surfaced
  through :func:`engine_report` and exported by ``bench.py`` so the win is
  driver-verified rather than asserted. Every hot path additionally emits
  structured events into the :mod:`torchmetrics_tpu.diag` flight recorder
  (dispatches, retraces with attributed cause, collectives, fallbacks), and
  the bench scenarios run under the diag strict transfer guard to prove the
  zero-host-transfer invariant — see ``docs/pages/observability.md``.
- :class:`~torchmetrics_tpu.engine.epoch.EpochEngine` /
  :class:`~torchmetrics_tpu.engine.epoch.CollectionEpoch` — the epoch-boundary
  half: packed single-collective sync
  (:class:`~torchmetrics_tpu.parallel.packing.PackedSyncPlan`: one metadata
  gather + one collective per (role, dtype) buffer for ALL states of a metric
  — or of every compute-group owner of a ``MetricCollection``) and cached
  ``compute()`` / fused ``sync→reduce-fold→compute`` executables keyed by
  state signature, with collectives-per-sync / bytes-moved / compute-retrace
  counters riding the same :func:`engine_report` surface.

Enablement is TPU-first: ``auto`` engages the engine when the default JAX
backend is an accelerator and stays out of the way on CPU (where dispatch is
cheap and donation is a no-op). Force it either way with
``TORCHMETRICS_TPU_ENGINE=1|0``, :func:`set_engine_enabled`, the
:func:`engine_context` manager, or per metric via ``Metric(compiled_update=...)``.

Semantics note: a compiled step runs the metric's own ``update`` body under
``jax.jit``. Value-dependent host work (e.g. ``validate_args=True`` tensor
validation, which calls ``np.unique`` on the inputs) cannot trace; such metrics
fall back to the eager path — permanently for that signature — and the fallback
is counted, never silent. Construct hot-loop metrics with
``validate_args=False`` to compile.
"""

from torchmetrics_tpu.engine.async_dispatch import async_context, set_async_dispatch
from torchmetrics_tpu.engine.compiled import CompiledUpdate
from torchmetrics_tpu.engine.config import (
    engine_context,
    engine_enabled,
    set_engine_enabled,
)
from torchmetrics_tpu.engine.epoch import CollectionEpoch, EpochEngine
from torchmetrics_tpu.engine.fusion import FusedUpdate
from torchmetrics_tpu.engine.numerics import (
    compensated_context,
    set_compensated,
    set_drift_rtol,
)
from torchmetrics_tpu.engine.persist import (
    PersistEnvelopeError,
    PersistIntegrityError,
    persist_context,
    persist_state,
    prewarm,
    set_persist_dir,
    warm_start,
)
from torchmetrics_tpu.engine.scan import scan_context, set_scan_steps
from torchmetrics_tpu.engine.statespec import (
    StateSpec,
    cse_context,
    register_state_spec,
    set_cse,
)
from torchmetrics_tpu.engine.stats import EngineStats, engine_report, reset_engine_stats
from torchmetrics_tpu.engine.txn import (
    QuarantinedBatchError,
    quarantine_context,
    quarantine_report,
    set_quarantine_mode,
)

__all__ = [
    "CollectionEpoch",
    "CompiledUpdate",
    "EngineStats",
    "EpochEngine",
    "FusedUpdate",
    "PersistEnvelopeError",
    "PersistIntegrityError",
    "QuarantinedBatchError",
    "StateSpec",
    "async_context",
    "compensated_context",
    "cse_context",
    "engine_context",
    "engine_enabled",
    "engine_report",
    "persist_context",
    "persist_state",
    "prewarm",
    "quarantine_context",
    "quarantine_report",
    "register_state_spec",
    "reset_engine_stats",
    "scan_context",
    "set_async_dispatch",
    "set_compensated",
    "set_cse",
    "set_drift_rtol",
    "set_engine_enabled",
    "set_persist_dir",
    "set_quarantine_mode",
    "set_scan_steps",
    "warm_start",
]
