"""Engine enablement and policy knobs.

Resolution order for "is the engine on?" (first hit wins):

1. per-metric ``Metric(compiled_update=True/False)`` — handled by the caller;
2. an active :func:`engine_context` / :func:`set_engine_enabled` override;
3. ``TORCHMETRICS_TPU_ENGINE`` env var (``"1"``/``"0"``);
4. auto: on when the default JAX backend is an accelerator (tpu/gpu), off on
   CPU — on CPU the per-op dispatch the engine removes costs microseconds, and
   buffer donation is a backend no-op, so compiling every metric would only tax
   test suites with XLA compile time.

Donation follows the same auto rule (donating on CPU is silently ignored by
JAX, so forcing it on is harmless — tests do exactly that to exercise the
protection logic).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Generator, Optional

# "axon" is the tunneled-TPU plugin's registration name; its devices report
# platform "tpu" (BENCH_r04 fused-gate evidence) but the default-backend string
# can surface either spelling depending on the jax version
_ACCELERATORS = ("tpu", "gpu", "cuda", "rocm", "axon")

# module-level override: None = defer to env var / auto
_enabled_override: Optional[bool] = None
_donate_override: Optional[bool] = None

#: The env-knob registry — every ``TORCHMETRICS_TPU_*`` variable the package
#: reads, mapped to its ONE recognized fail-loud parser (``module:qualname``).
#: The static analyzer (``tools/tmlint`` rule TM201) rejects any
#: ``os.environ``/``os.getenv`` read of a registered key outside its parser,
#: flags reads of UNregistered ``TORCHMETRICS_TPU_*`` keys, and cross-checks
#: this table against the knob documentation in ``docs/api/root.md`` (TM203/
#: TM204) — so "implemented but undocumented" and "documented but gone" both
#: fail CI from the source text. Adding a knob means: write the fail-loud
#: parser (the PR-7 env contract), register it here, document it in
#: ``docs/api/root.md``.
KNOB_REGISTRY = {
    "TORCHMETRICS_TPU_ENGINE": "torchmetrics_tpu.engine.config:engine_enabled",
    "TORCHMETRICS_TPU_CSE": "torchmetrics_tpu.engine.statespec:cse_enabled",
    "TORCHMETRICS_TPU_SCAN": "torchmetrics_tpu.engine.scan:scan_k",
    "TORCHMETRICS_TPU_ASYNC": "torchmetrics_tpu.engine.async_dispatch:async_inflight",
    "TORCHMETRICS_TPU_QUARANTINE": "torchmetrics_tpu.engine.txn:quarantine_mode",
    "TORCHMETRICS_TPU_COMPENSATED": "torchmetrics_tpu.engine.numerics:compensated_enabled",
    "TORCHMETRICS_TPU_DRIFT_RTOL": "torchmetrics_tpu.engine.numerics:drift_rtol",
    "TORCHMETRICS_TPU_SHARD": "torchmetrics_tpu.parallel.sharding:_env_mesh",
    "TORCHMETRICS_TPU_MULTIHOST": "torchmetrics_tpu.parallel.sharding:multihost_spec",
    "TORCHMETRICS_TPU_SYNC_DEADLINE_MS": "torchmetrics_tpu.parallel.resilience:_env_float",
    "TORCHMETRICS_TPU_SYNC_RETRIES": "torchmetrics_tpu.parallel.resilience:_env_float",
    "TORCHMETRICS_TPU_SYNC_BACKOFF_MS": "torchmetrics_tpu.parallel.resilience:_env_float",
    "TORCHMETRICS_TPU_DEGRADED": "torchmetrics_tpu.parallel.resilience:current_policy",
    "TORCHMETRICS_TPU_SNAPSHOT_EVERY": "torchmetrics_tpu.parallel.elastic:SnapshotPolicy.from_env",
    "TORCHMETRICS_TPU_COSTS": "torchmetrics_tpu.diag.costs:costs_enabled",
    "TORCHMETRICS_TPU_TRACE": "torchmetrics_tpu.diag.trace:_env_recorder",
    "TORCHMETRICS_TPU_SENTINEL": "torchmetrics_tpu.diag.sentinel:sentinel_enabled",
    "TORCHMETRICS_TPU_AUDIT": "torchmetrics_tpu.diag.sentinel:audit_enabled",
    "TORCHMETRICS_TPU_PROFILE": "torchmetrics_tpu.diag.profile:active_profile",
    "TORCHMETRICS_TPU_STRAGGLER_US": "torchmetrics_tpu.diag.profile:straggler_threshold_us",
    "TORCHMETRICS_TPU_SERVE_CAPACITY": "torchmetrics_tpu.serve.stats:_env_int",
    "TORCHMETRICS_TPU_SERVE_PORT": "torchmetrics_tpu.serve.stats:_env_int",
    "TORCHMETRICS_TPU_SERVE_SNAPSHOT_RETRIES": "torchmetrics_tpu.serve.stats:_env_int",
    # heavy-workload kernels (PR 15): FID host-eigh fallback + BERTScore buckets
    "TORCHMETRICS_TPU_FID_HOST_EIGH": "torchmetrics_tpu.image.fid:fid_host_eigh",
    "TORCHMETRICS_TPU_BERT_BUCKETS": "torchmetrics_tpu.functional.text.bert:bert_buckets_enabled",
    # persistent executable cache (PR 17): zero-cold-start serving
    "TORCHMETRICS_TPU_PERSIST": "torchmetrics_tpu.engine.persist:persist_dir",
    # federated aggregation plane (PR 18): cross-pod global folds
    "TORCHMETRICS_TPU_FEDERATION_STALENESS_S": "torchmetrics_tpu.parallel.resilience:_env_float",
    "TORCHMETRICS_TPU_FEDERATION_TIMEOUT_MS": "torchmetrics_tpu.parallel.resilience:_env_float",
    "TORCHMETRICS_TPU_FEDERATION_RETRIES": "torchmetrics_tpu.serve.stats:_env_int",
    # fleet observability plane + SLO engine (PR 19)
    "TORCHMETRICS_TPU_FLEET_PULL_MS": "torchmetrics_tpu.serve.stats:_env_int",
    "TORCHMETRICS_TPU_SLO": "torchmetrics_tpu.diag.slo:_env_slo",
    # value provenance & freshness plane (PR 20)
    "TORCHMETRICS_TPU_LINEAGE": "torchmetrics_tpu.diag.lineage:lineage_enabled",
}

#: parsers that read the env key through a ``name`` PARAMETER (shared
#: validation helpers) — the only functions where a dynamic (non-literal)
#: environ key read is sanctioned (tmlint rule TM202)
GENERIC_KNOB_PARSERS = (
    "torchmetrics_tpu.parallel.resilience:_env_float",
    "torchmetrics_tpu.serve.stats:_env_int",
)

# bucketing policy (see engine/bucketing.py)
BUCKETING_ENABLED = True
MIN_BUCKET = 8


def _default_backend() -> str:
    # shared with the fused-op dispatch gates: init failure degrades to "cpu"
    from torchmetrics_tpu.ops._dispatch import default_backend

    return default_backend()


def engine_enabled() -> bool:
    """Whether the fused update engine engages for metrics without a per-metric override."""
    if _enabled_override is not None:
        return _enabled_override
    env = os.environ.get("TORCHMETRICS_TPU_ENGINE")
    if env is not None and env.strip() in ("0", "1"):
        return env.strip() == "1"
    return _default_backend() in _ACCELERATORS


def set_engine_enabled(value: Optional[bool]) -> None:
    """Force the engine on/off process-wide; ``None`` restores auto resolution."""
    global _enabled_override
    if value is not None and not isinstance(value, bool):
        raise ValueError(f"Expected `value` to be a bool or None but got {value}")
    _enabled_override = value


def donation_enabled() -> bool:
    """Whether compiled steps donate their state buffers."""
    if _donate_override is not None:
        return _donate_override
    return _default_backend() in _ACCELERATORS


def set_donation_enabled(value: Optional[bool]) -> None:
    """Force donation on/off (``None`` = auto). Donation on CPU is a JAX no-op."""
    global _donate_override
    _donate_override = value


@contextmanager
def engine_context(enabled: bool = True, donate: Optional[bool] = None) -> Generator:
    """Scoped engine enablement — the bench and the tests use this."""
    global _enabled_override, _donate_override
    prev_e, prev_d = _enabled_override, _donate_override
    _enabled_override = enabled
    if donate is not None:
        _donate_override = donate
    try:
        yield
    finally:
        _enabled_override, _donate_override = prev_e, prev_d
