"""Multi-step scan dispatch — fold K queued update steps into ONE donated
``lax.scan`` executable.

The per-step hot loop is host-dispatch-dominated: the XLA ledger shows tiny
device work while an engine step costs hundreds of µs of Python + launch
overhead on CPU (BENCH_r10–r13). This module amortizes the dispatch itself:
a per-owner :class:`ScanQueue` buffers up to ``K`` update payloads that share
one compile signature (treedef, bucketed shapes/dtypes), then drains them
through a single cached executable whose body is ``lax.scan`` over the queued
axis — each scan step re-runs the engine's OWN per-step composition
(:func:`~torchmetrics_tpu.engine.compiled.make_step_body`: update body →
pad-subtract → compensated two-sum → quarantine transaction) against the
donated state carry, so K steps cost one dispatch instead of K.

Design points:

- **K-buckets + masked padding.** A drain of ``S ≤ K`` steps pads up to the
  next power-of-two ``k_bucket(S)`` and masks the pad steps with a traced
  ``valid`` flag (``jnp.where(valid, new, carry)`` per leaf), so ragged queue
  tails reuse O(log K) executables instead of compiling one per tail length —
  the same philosophy as ``engine/bucketing.py``'s pad-subtract. Pad steps
  replay the LAST real step's input arrays (no allocation); the mask
  guarantees their values, sentinel bits, quarantine verdicts, and residual
  contributions never land in state.
- **Rider composition per scan step.** The quarantine admission + rollback
  select evaluates per step inside the scan body, so a poisoned step skips
  only itself (the carry flows on); compensated two-sum accumulation runs per
  step against the carried residual; sentinel bits OR across steps; the
  ``__sentinel__``/``__quarantine__``/``__compensation__`` reserved keys ride
  the carry like any other state leaf.
- **Flush points.** The queue drains on: signature change, K reached, and ANY
  state observation — ``compute()``, ``sync()``, ``forward()``,
  ``state_dict()``, ``merge_state``, cloning/pickling, device moves,
  ``snapshot_compute()``/``take_snapshot``, and sidecar scrapes via
  ``serve/snapshot.read_host`` — each recorded as a ``scan.flush`` event with
  its reason. A scrape can therefore never observe state that is K steps
  stale. ``reset()`` DISCARDS the queue instead (applying updates that the
  reset immediately wipes is byte-identical to skipping them).
- **Async background drains.** With ``engine/async_dispatch.py`` enabled, a
  full (or signature-changed) buffer is SWAPPED out under the queue lock and
  drained on a bounded background worker while the caller fills the next
  buffer — ``update()`` becomes a pure enqueue. Every flush point above turns
  into a JOIN: the observer waits out the in-flight drains (and replays any
  payloads a failed worker drain handed back) before the state read; the hot
  loop never pays a drain, a join, or a replay. Sync-mode behavior is
  byte-identical and untouched.
- **Donation-stable carry.** ``lax.scan`` needs a fixed carry signature, but
  an update body may promote dtypes (the x64 first-update int32→int64
  widening). The compile pre-resolves the body's output dtypes via
  ``jax.eval_shape`` and casts the incoming state once, up front — exactly
  the state the one-step engine would hold after its first update — and
  requires a fixed point (a body that keeps reshaping its state cannot scan
  and replays step-at-a-time, counted).

Enablement (first hit wins; invalid values FAIL LOUD per the PR-7 env
contract): per-metric ``Metric(scan_steps=K)`` /
``MetricCollection(scan_steps=K)`` (``0``/``False`` forces off), an active
:func:`scan_context` / :func:`set_scan_steps` override, then
``TORCHMETRICS_TPU_SCAN=K``. The queue additionally requires the engine
itself to be enabled — scan rides the compiled-step machinery.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import deque
from contextlib import contextmanager
from time import perf_counter
from typing import Any, Deque, Dict, FrozenSet, Generator, List, Optional, Sequence, Set, Tuple

import numpy as np

from torchmetrics_tpu.diag import costs as _costs
from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import lineage as _lineage
from torchmetrics_tpu.diag import profile as _profile
from torchmetrics_tpu.diag import sentinel as _sentinel
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.engine import bucketing, config
from torchmetrics_tpu.engine import numerics as _numerics
from torchmetrics_tpu.engine import persist as _persist
from torchmetrics_tpu.engine import txn as _txn
from torchmetrics_tpu.engine.compiled import (
    _FALLBACK,
    _Ineligible,
    _is_jax_array,
    annotation_scope,
    build_riders,
    build_run,
    completion_probe,
    input_signature,
    make_step_body,
    shield_state,
    signature_fingerprint,
    state_invalidated,
    state_signature,
)
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "MAX_K",
    "SCAN_ENV_VAR",
    "coerce_k",
    "discard_metric",
    "discard_metrics",
    "flush_all",
    "flush_metric",
    "flush_metrics",
    "k_bucket",
    "scan_context",
    "scan_k",
    "set_scan_steps",
]

SCAN_ENV_VAR = "TORCHMETRICS_TPU_SCAN"

#: upper bound on the queue depth — past ~1k steps the stacked inputs' device
#: footprint (K x input bytes) dwarfs any remaining dispatch amortization
MAX_K = 1024

#: K-buckets up to this size compile FULLY UNROLLED (no lax.scan machinery);
#: deeper queues ride a bounded-unroll lax.scan so compile time stays flat
UNROLL_MAX = 32

_UNSET = object()
_k_override: Any = _UNSET


# ------------------------------------------------------------------ policy


def coerce_k(value: Any) -> Optional[int]:
    """Validate a queue-depth knob: ``0``/``False`` = forced off, int in
    [2, MAX_K] = depth; ``None`` passes through (defer to the policy)."""
    if value is None:
        return None
    if isinstance(value, bool):
        if value:
            raise TorchMetricsUserError(
                "scan_steps=True is ambiguous — pass the queue depth K (an int >= 2),"
                " or 0/False to disable the queue"
            )
        return 0
    if isinstance(value, int):
        if value == 0:
            return 0
        if 2 <= value <= MAX_K:
            return value
    raise TorchMetricsUserError(
        f"scan queue depth must be 0 (off) or an integer in [2, {MAX_K}] (got {value!r});"
        " K=1 is the unqueued engine — leave the knob unset instead"
    )


def scan_k() -> Optional[int]:
    """The active queue depth K, or ``None`` when multi-step scan is off.

    An unrecognized ``TORCHMETRICS_TPU_SCAN`` value fails loud (the PR-7 env
    contract): a typo must not silently disable the amortization it was set
    to enable — nor silently enable a nonsense depth.
    """
    if _k_override is not _UNSET:
        return _k_override or None
    raw = os.environ.get(SCAN_ENV_VAR, "").strip().lower()
    if raw in ("", "0", "off"):
        return None
    try:
        k = int(raw)
    except ValueError:
        raise TorchMetricsUserError(
            f"{SCAN_ENV_VAR}={raw!r} is not a valid queue depth (expected unset/'0'/'off'"
            f" or an integer K in [2, {MAX_K}])"
        ) from None
    if not (2 <= k <= MAX_K):
        raise TorchMetricsUserError(
            f"{SCAN_ENV_VAR}={k} is out of range: K must be in [2, {MAX_K}]"
            " (K=1 is the unqueued engine — unset the variable instead)"
        )
    return k


def set_scan_steps(value: Optional[Any]) -> None:
    """Force the queue depth process-wide (``0``/``False`` = off); ``None``
    restores env resolution."""
    global _k_override
    _k_override = _UNSET if value is None else coerce_k(value)


@contextmanager
def scan_context(k: int = 8) -> Generator[None, None, None]:
    """Scoped multi-step scan enablement (benches, tests, serving loops).

    Exiting the scope FLUSHES every queue with pending steps (reason
    ``scope-exit``) — state outside the scope is never stale — and restores
    the previous policy.
    """
    global _k_override
    prev = _k_override
    _k_override = coerce_k(k)
    try:
        yield
    finally:
        try:
            flush_all("scope-exit")
        finally:
            # restore even when a drain raises: a flush failure must not leak
            # the forced depth process-wide
            _k_override = prev


def k_bucket(n: int) -> int:
    """Smallest power-of-two scan length holding ``n`` queued steps."""
    b = 1
    while b < n:
        b <<= 1
    return b


# ------------------------------------------------------------------ registry

_seq_counter = iter(range(1, 1 << 62))
#: live queues, weakly held (a queue lives exactly as long as its engine)
_QUEUES: "weakref.WeakValueDictionary[int, _ScanQueue]" = weakref.WeakValueDictionary()


def flush_metric(metric: Any, reason: str) -> int:
    """Drain every queue holding pending steps for ``metric``; returns steps drained."""
    if not _QUEUES:
        return 0
    drained = 0
    for q in list(_QUEUES.values()):
        if q.pending and q.owns(metric):
            drained += q.drain(reason)
    return drained


def flush_metrics(metrics: Sequence[Any], reason: str) -> int:
    """Drain every queue holding pending steps for ANY of ``metrics``."""
    if not _QUEUES:
        return 0
    drained = 0
    for q in list(_QUEUES.values()):
        if q.pending and any(q.owns(m) for m in metrics):
            drained += q.drain(reason)
    return drained


def flush_all(reason: str) -> int:
    """Drain every live queue (scope exit, sidecar scrape)."""
    if not _QUEUES:
        return 0
    drained = 0
    for q in list(_QUEUES.values()):
        if q.pending:
            drained += q.drain(reason)
    return drained


def discard_metric(metric: Any, reason: str) -> int:
    """Drop ``metric``'s pending steps WITHOUT dispatching (the reset path).

    Discard is only byte-identical for queues the resetting metric owns
    EXCLUSIVELY (its per-metric queue): a shared fused queue also carries the
    sibling members' enqueued steps, so it DRAINS instead — the siblings get
    their updates, and the caller's reset then wipes its own folded share
    (identical to having skipped it).
    """
    if not _QUEUES:
        return 0
    dropped = 0
    for q in list(_QUEUES.values()):
        if q.pending and q.owns(metric):
            if q.exclusive_to((metric,)):
                dropped += q.discard(reason)
            else:
                dropped += q.drain(reason)
    return dropped


def discard_metrics(metrics: Sequence[Any], reason: str) -> int:
    """Collection-reset discard: queues owned entirely WITHIN ``metrics`` drop
    their payloads; a queue sharing members outside the set drains instead."""
    if not _QUEUES:
        return 0
    dropped = 0
    for q in list(_QUEUES.values()):
        if q.pending and any(q.owns(m) for m in metrics):
            if q.exclusive_to(metrics):
                dropped += q.discard(reason)
            else:
                dropped += q.drain(reason)
    return dropped


# ------------------------------------------------------------------ the scan executable


def compile_scan(body, example_state, example_inputs: Sequence[Any], kb: int, owner: str, key: Tuple, stats):
    """Jit + AOT-compile the K-folding scan over ``body`` (the per-step
    composition from :func:`~torchmetrics_tpu.engine.compiled.make_step_body`).

    The executable's signature is ``(state, valid[kb], n_pads[kb],
    *flat_steps)`` with ``flat_steps`` holding ``kb`` step-major groups of the
    per-step inputs; the inputs stack INSIDE the graph (no host-side device
    ops, one dispatch per drain) and the state carry is donated.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_in = len(example_inputs)

    def abstract_body(s, f):
        return body(s, np.int32(0), tuple(f))

    # carry-signature stabilization: resolve the body's output dtypes once and
    # cast the incoming state up front (the x64 first-update promotion), then
    # require a fixed point — lax.scan cannot carry a changing signature
    out_shapes = jax.eval_shape(abstract_body, example_state, list(example_inputs))
    out_tree = jax.tree_util.tree_structure(out_shapes)
    if jax.tree_util.tree_structure(example_state) != out_tree:
        raise _Ineligible("scan carry structure changes under the update body")
    out_leaves = jax.tree_util.tree_leaves(out_shapes)
    in_leaves = jax.tree_util.tree_leaves(example_state)
    for a, b in zip(in_leaves, out_leaves):
        if tuple(a.shape) != tuple(b.shape):
            raise _Ineligible("scan carry shape changes under the update body")
    carry_dtypes = jax.tree_util.tree_unflatten(out_tree, [leaf.dtype for leaf in out_leaves])
    cast_example = jax.tree_util.tree_unflatten(
        out_tree,
        [jax.ShapeDtypeStruct(tuple(a.shape), b.dtype) for a, b in zip(in_leaves, out_leaves)],
    )
    fixed = jax.eval_shape(abstract_body, cast_example, list(example_inputs))
    for a, b in zip(jax.tree_util.tree_leaves(fixed), out_leaves):
        if a.dtype != b.dtype or tuple(a.shape) != tuple(b.shape):
            raise _Ineligible("scan carry does not reach a dtype fixed point")

    def masked_step(carry, valid_t, n_pad_t, flat_t):
        new = body(carry, n_pad_t, flat_t)
        # masked no-op padding: an invalid (pad) step selects the carry
        # back leaf-wise — its values, sentinel bits, quarantine verdict,
        # and residual contribution all evaporate
        return jax.tree_util.tree_map(
            lambda nv, ov: jnp.where(valid_t, nv, ov), new, carry
        )

    if kb <= UNROLL_MAX:
        # small K-buckets trace FULLY UNROLLED: the step inputs feed the
        # bodies directly (no stack, no per-step dynamic slice, no While-loop
        # carry round-trip — all measurable against the tiny bodies on CPU)
        # and XLA fuses across the steps

        def scan_fn(state, valid, n_pads, *flat_steps):
            carry = jax.tree_util.tree_map(
                lambda v, d: v.astype(d) if v.dtype != d else v, state, carry_dtypes
            )
            for t in range(kb):
                flat_t = flat_steps[t * n_in : (t + 1) * n_in]
                carry = masked_step(carry, valid[t], n_pads[t], flat_t)
            return carry

    else:

        def scan_fn(state, valid, n_pads, *flat_steps):
            state = jax.tree_util.tree_map(
                lambda v, d: v.astype(d) if v.dtype != d else v, state, carry_dtypes
            )
            cols = tuple(
                jnp.stack([flat_steps[t * n_in + j] for t in range(kb)]) for j in range(n_in)
            )

            def scan_body(carry, xs):
                return masked_step(carry, xs[0], xs[1], xs[2:]), None

            # deep queues ride a real lax.scan with a bounded partial unroll:
            # compile time stays O(UNROLL_MAX) bodies regardless of K
            final, _ = lax.scan(
                scan_body, state, (valid, n_pads) + cols, unroll=8
            )
            return final

    donate = config.donation_enabled()
    # SPMD carry (parallel/sharding.py): sharded state leaves pin their
    # NamedSharding on the scan output so the whole K-fold drain lowers as
    # one SPMD program and the donated carry stays partitioned in place
    from torchmetrics_tpu.parallel import sharding as _sharding

    out_sh = _sharding.state_out_shardings(example_state)
    jit_kwargs = {"donate_argnums": (0,) if donate else ()}
    if out_sh is not None:
        jit_kwargs["out_shardings"] = out_sh
    fn = jax.jit(scan_fn, **jit_kwargs)
    example_valid = np.zeros((kb,), np.bool_)
    example_valid[:1] = True
    example_pads = np.zeros((kb,), np.int32)
    example_flat: List[Any] = []
    for _ in range(kb):
        example_flat.extend(example_inputs)
    state_bytes = sum(getattr(leaf, "nbytes", 0) for leaf in in_leaves)
    fn = _costs.aot_compile(
        fn,
        owner=owner,
        kind="scan",
        args=(example_state, example_valid, example_pads, *example_flat),
        donated_bytes=state_bytes if donate else 0,
        stats=stats,
    )
    # prewarm manifest: per-step input specs + the K-bucket — prewarm replays
    # kb zero updates inside a scan_context(kb) so the drain rebuilds this
    # exact executable signature
    _persist.record_compile(owner, "scan", args=list(example_inputs), k=kb)
    step_in_bytes = sum(getattr(a, "nbytes", 0) for a in example_inputs)
    return fn, donate, annotation_scope(owner, "scan", key), state_bytes, step_in_bytes


def write_member_state(m: Any, out: Dict[str, Any], steps: int, stats) -> Optional[Dict[str, Any]]:
    """One member's drain writeback: rider pops + state setattrs under the
    PR-7 mutation guard (a SIGTERM snapshot landing mid-writeback must see a
    mutation in flight, never persist a torn half-written state). Shared by
    the per-metric and the fused queues so the rider handling cannot drift.
    Returns the residual dict for the caller's drift-probe decision.
    """
    m._mutation_depth = getattr(m, "_mutation_depth", 0) + 1
    try:
        sentinel_out = out.pop(_sentinel.STATE_KEY, None)
        if sentinel_out is not None:
            setattr(m, _sentinel.ATTR, sentinel_out)
        quarantine_out = out.pop(_txn.STATE_KEY, None)
        if quarantine_out is not None:
            setattr(m, _txn.ATTR, quarantine_out)
        residual_out = out.pop(_numerics.STATE_KEY, None)
        if residual_out is not None:
            setattr(m, _numerics.ATTR, residual_out)
            stats.compensated_steps += steps
        for name, v in out.items():
            setattr(m, name, v)
    finally:
        m._mutation_depth -= 1
    return residual_out


# ------------------------------------------------------------------ queues


class _DrainWork:
    """One swapped-out buffer: everything a drain needs, caller-independent.

    The queue's live ``_qkey``/``_k``/member names may move on under the
    enqueueing thread while this buffer waits behind the background worker —
    the work item freezes the values the drain must compile and write back
    against. ``first_wait_t`` is the overlap boundary: the instant the first
    caller blocked on this item, the caller's forward progress (the thing
    ``overlap_us`` attributes) ended.
    """

    __slots__ = (
        "queue", "pending", "qkey", "k", "names", "reason",
        "done", "ctx", "replay", "error", "first_wait_t", "lineage",
    )

    def __init__(self, queue: "_ScanQueue", pending, qkey, k: int, names, reason: str) -> None:
        self.queue = queue
        self.pending = pending
        self.qkey = qkey
        self.k = k
        self.names = names
        self.reason = reason
        self.done = threading.Event()
        self.ctx = None  # contextvars snapshot, stamped at submit
        self.replay = False  # worker handed the payload back for caller replay
        self.error = None  # exception to re-raise at the join (state consumed)
        self.first_wait_t: Optional[float] = None
        self.lineage: Optional[int] = None  # causal span id, stamped at swap


class _ScanQueue:
    """Per-owner step queue + drain machinery (shared core).

    Subclasses bind the queue to its engine: :class:`MetricScan` to one
    metric's :class:`~torchmetrics_tpu.engine.compiled.CompiledUpdate`,
    :class:`FusedScan` to a collection's
    :class:`~torchmetrics_tpu.engine.fusion.FusedUpdate`.
    """

    def __init__(self, stats) -> None:
        self.stats = stats
        #: (orig_args, orig_kwargs, padded_inputs, n_pad) per queued step
        self._pending: List[Tuple[Tuple, Dict, Tuple, int]] = []  # guarded-by: _lock
        self._qkey: Optional[Tuple] = None  # guarded-by: _lock
        self._k = 0  # guarded-by: _lock
        self._cache: Dict[Tuple, Any] = {}  # guarded-by: _drain_mutex
        self._fingerprints: Dict[Tuple, Dict[str, Any]] = {}  # guarded-by: _drain_mutex
        self._transient_fails: Dict[Tuple, int] = {}  # guarded-by: _drain_mutex
        # drains can fire from a sidecar scrape thread while the hot loop
        # enqueues: the reentrant lock serializes dequeue+dispatch+writeback
        # so two flushes can never double-apply one payload
        self._lock = threading.RLock()
        #: optional post-drain hook (a collection re-anchoring its group views
        #: after a drain donated an owner's buffers — wherever the drain fired)
        self.on_drain = None
        # --- async tier (engine/async_dispatch.py) -----------------------
        #: in-flight bound resolved at push time (None/0 = synchronous drains)
        self._async_limit: Optional[int] = None  # guarded-by: _lock
        #: buffers swapped out inside _push_locked, submitted OUTSIDE the lock
        self._staged_work: List[_DrainWork] = []  # guarded-by: _lock
        self._needs_join = False  # guarded-by: _lock
        #: FIFO of submitted-but-unjoined work (pruned lazily as items finish)
        self._inflight: Deque[_DrainWork] = deque()  # guarded-by: _lock
        #: payloads a failed worker drain handed back for caller-side replay
        self._failed: Deque[_DrainWork] = deque()  # guarded-by: _lock
        #: a worker failure stops dispatching until a join replays the FIFO —
        #: otherwise later buffers would apply ahead of the failed one
        self._poisoned = False  # guarded-by: _lock
        #: a successful background drain defers the view re-anchor to the join
        self._post_pending = False  # guarded-by: _lock
        # worker execution vs a caller-side synchronous drain of the SAME
        # queue: one mutex serializes gather/dispatch/writeback. Callers that
        # hold self._lock may acquire it; the worker takes it WITHOUT
        # self._lock, so the ordering is one-directional and deadlock-free
        self._drain_mutex = threading.Lock()
        _QUEUES[next(_seq_counter)] = self

    # -- interface subclasses provide -----------------------------------

    def owns(self, metric: Any) -> bool:
        raise NotImplementedError

    def exclusive_to(self, metrics: Sequence[Any]) -> bool:
        """Whether every metric this queue folds into is within ``metrics``
        (discard safety: dropping the queue loses no other metric's steps)."""
        raise NotImplementedError

    def _gather_state(self, names):
        """(state_pytree, state_sig, device_token) for the drain, or None."""
        raise NotImplementedError

    def _compile_entry(self, example_state, example_inputs, kb: int, key: Tuple, work: _DrainWork):
        raise NotImplementedError

    def _shield(self, state, names):
        raise NotImplementedError

    def _invalidated(self, names) -> bool:
        raise NotImplementedError

    def _writeback(self, out, steps: int, probing: bool, names) -> None:
        raise NotImplementedError

    def _replay(self, pending, names) -> None:
        raise NotImplementedError

    def _fingerprint(self, state_sig, kb: int, device: str, qkey) -> Dict[str, Any]:
        raise NotImplementedError

    def _names_snapshot(self):
        """Member-name freeze for a work item (fused queues override)."""
        return None

    def _note_discarded(self, names, steps: int) -> None:
        """Realign the provenance watermark for dropped payloads (discard
        path): the steps will never fold, so they stop counting as staleness
        but stay on the record as a ``discarded`` exclusion."""
        _lineage.note_discarded(self.stats.owner, steps)

    def _post_drain(self) -> None:
        """Hook after a successful drain (view re-anchoring for collections)."""
        cb = self.on_drain
        if cb is not None:
            cb()

    # -- queue core ------------------------------------------------------

    @property
    def pending(self) -> int:
        # in-flight and handed-back buffers count: an observation must JOIN
        # them even when the active buffer is empty
        with self._lock:
            return (
                len(self._pending)
                + sum(len(w.pending) for w in self._inflight)
                + sum(len(w.pending) for w in self._failed)
            )

    def push(self, args: Tuple[Any, ...], kwargs: Dict[str, Any], k: int, async_inflight: Optional[int] = None):
        """Queue one payload (see the subclass ``_push_locked`` for the
        semantics of the return value). Async staging, submits, and joins all
        happen OUTSIDE the queue lock, so the worker — which takes the drain
        mutex but never this lock from its own stack — cannot deadlock
        against an enqueue."""
        # tmlint: disable=TM601 — emptiness peek; a stale read only skips the
        # early join, and join_async re-checks the FIFOs under the lock
        if not async_inflight and (self._inflight or self._failed):
            # async was just disabled mid-stream (scope exit, kwarg change):
            # the leftover background work must land before this step's path
            # — synchronous or eager — applies, or batches would reorder
            self.join_async("async-disabled")
        measuring = async_inflight and (
            _diag.active_recorder() is not None or _profile.active_profile() is not None
        )
        t0 = perf_counter() if measuring else 0.0
        with self._lock:
            self._async_limit = async_inflight or None
            result = self._push_locked(args, kwargs, k)
            staged, self._staged_work = self._staged_work, []
            needs_join, self._needs_join = self._needs_join, False
        try:
            for idx, work in enumerate(staged):
                self._submit_work(work)
        except BaseException:
            # a failed submit (a stored drain error re-raised at a join, a
            # wedged executor) must not leave later staged buffers tracked
            # but never-completing — observers would wait on them forever.
            # Hand them to the failed FIFO: the next join replays them.
            for w in staged[idx:]:
                self._abandon(w)
            raise
        if needs_join:
            # an OBSERVING flush point fired inside the enqueue (ineligible
            # step about to run eagerly): ordering requires the staged buffer
            # to fully land before the caller proceeds
            self.join_async("enqueue-ineligible")
        if measuring:
            # the full caller-side cost of this enqueue, submits and
            # backpressure waits included — the p50 of this distribution IS
            # the "update() ≈ a dict append" claim, measured
            _hist.observe(self.stats.owner, "async", "enqueue_us", round((perf_counter() - t0) * 1e6, 3))
        return result

    def discard(self, reason: str) -> int:
        """Drop the queued payloads without dispatching (reset semantics).

        Async tier: background drains already in flight complete first (the
        caller's reset wipes their folded effect — byte-identical to having
        skipped them); failed hand-backs are DROPPED like the pending buffer
        (replaying then wiping equals skipping).
        """
        self.join_async(reason, collect=False)
        with self._lock:
            # per-source step counts: failed hand-backs keep their frozen
            # member names (the fused watermark must realign the members the
            # steps were actually enqueued for)
            drops = [(self._names_snapshot(), len(self._pending))]
            drops += [(w.names, len(w.pending)) for w in self._failed]
            n = sum(steps for _, steps in drops)
            self._failed.clear()
            self._poisoned = False
            if not n:
                return 0
            self._pending = []
        st = self.stats
        st.scan_flushes += 1
        st.scan_flush_reasons[reason] += 1
        for names, steps in drops:
            if steps:
                self._note_discarded(names, steps)
        _diag.record("scan.flush", st.owner, reason=reason, steps=n, discarded=True)
        return n

    def drain(self, reason: str) -> int:
        """Fold every queued step into state through one scan dispatch.

        This is the async tier's JOIN point: with async dispatch active the
        current buffer rides the background worker too — the OBSERVER waits
        for it, while the hot loop only ever contends on the buffer swap.
        """
        drained = self.join_async(reason)
        with self._lock:
            if not self._async_limit:
                return drained + self._drain_locked(reason)
            work = self._swap_locked(reason)
            if work is not None:
                self._inflight.append(work)  # joinable from the swap instant
        if work is None:
            return drained
        try:
            self._submit_work(work)
        except BaseException:
            self._abandon(work)
            raise
        self.join_async(reason)
        return drained + len(work.pending)

    # tmlint: holds(_lock)
    def _drain_locked(self, reason: str) -> int:
        """Synchronous drain (queue lock held): swap + execute on this thread."""
        work = self._swap_locked(reason)
        if work is None:
            return 0
        with self._drain_mutex:
            ok = self._execute_work(work)
        if not ok:
            self._replay(work.pending, work.names)
        # the replay's one-step dispatches donate too: views re-anchor
        self._post_drain()
        return len(work.pending)

    # tmlint: holds(_lock)
    def _flush_point_locked(self, reason: str, asyncable: bool) -> None:
        """A drain trigger inside the enqueue path (queue lock held).

        Async mode swaps the buffer for the background worker — ``k-reached``
        and ``signature-change`` are pure ordering points, nothing observes
        state at them. A trigger followed by an eager step in the same push
        (``asyncable=False``) additionally forces a join before ``push``
        returns, so the eager step cannot overtake the swapped buffer.
        """
        if self._async_limit:
            work = self._swap_locked(reason)
            if work is not None:
                # tracked from the SWAP (still under the queue lock): the
                # buffer is visible to `pending` and joinable by a concurrent
                # observer from the first instant it leaves the active list —
                # there is no window where its steps are invisible
                self._inflight.append(work)
                self._staged_work.append(work)
            if not asyncable:
                self._needs_join = True
        else:
            self._drain_locked(reason)

    # tmlint: holds(_lock)
    def _swap_locked(self, reason: str) -> Optional[_DrainWork]:
        """Detach the active buffer as a work item (the double-buffer swap)."""
        pending = self._pending
        n = len(pending)
        if not n:
            return None
        self._pending = []
        st = self.stats
        st.scan_flushes += 1
        st.scan_flush_reasons[reason] += 1
        # the open causal span leaves the queue with the buffer: the id links
        # this swap's enqueues to the drain/join events that settle them
        span = _lineage.take_span(st.owner)
        rec = _diag.active_recorder()
        if rec is not None:
            if span is not None:
                rec.record("scan.flush", st.owner, reason=reason, steps=n, lineage=span)
            else:
                rec.record("scan.flush", st.owner, reason=reason, steps=n)
        work = _DrainWork(self, pending, self._qkey, self._k, self._names_snapshot(), reason)
        work.lineage = span
        return work

    # tmlint: holds(_drain_mutex)
    def _execute_work(self, work: _DrainWork, allow_compile: bool = True) -> bool:
        """Gather → (compile) → ONE dispatch → counters → writeback.

        Runs on the caller (sync path) or the background worker (async path),
        always under the drain mutex. Returns False when the payload must
        replay step-at-a-time; raises when donation already consumed the
        state (nothing intact to replay). ``allow_compile=False`` (the worker)
        refuses a first compile outright — tracing diffs the metric __dict__
        against the caller's live enqueue bookkeeping, so compiles belong to
        the caller's thread; a refused buffer replays there instead.
        """
        pending = work.pending
        n = len(pending)
        st = self.stats
        rec = _diag.active_recorder()
        gathered = self._gather_state(work.names)
        if gathered is None:
            st.fallback("scan-state-ineligible")
            return False
        state, state_sig, device = gathered
        kb = k_bucket(n)
        pad = kb - n
        key = (work.qkey, state_sig, device, kb)
        entry = self._cache.get(key)
        if entry is _FALLBACK:
            st.fallback("scan-uncompilable-signature")
            return False
        first = entry is None
        if first and not allow_compile:
            # the submit-side key prediction raced an in-flight writeback
            # (e.g. the x64 widening moved the signature under it): hand the
            # payload back rather than trace on the worker
            st.fallback("scan-async-warm-miss")
            return False

        # step-major flat args; pad steps reuse the LAST real step's arrays
        # (no allocation — the valid mask makes them no-ops)
        flat_steps: List[Any] = []
        n_pads = np.zeros((kb,), np.int32)
        valid = np.zeros((kb,), np.bool_)
        for t in range(kb):
            src = pending[t] if t < n else pending[n - 1]
            flat_steps.extend(src[2])
            n_pads[t] = src[3]
            valid[t] = t < n

        profiling = _profile.active_profile() is not None
        measuring = rec is not None or profiling
        t_dispatch = perf_counter() if measuring else 0.0
        try:
            if first:
                entry = self._compile_entry(state, pending[0][2], kb, key, work)
            fn, donate, scope, state_bytes, step_in_bytes = entry
            if donate:
                state = self._shield(state, work.names)
            if measuring:
                t_dispatch = perf_counter()
            import jax

            with jax.profiler.TraceAnnotation(scope):
                out = fn(state, valid, n_pads, *flat_steps)
        except Exception as exc:  # noqa: BLE001 — a failed drain replays step-at-a-time
            if self._invalidated(work.names):
                raise  # donation consumed the state; nothing intact to replay
            # first-compile AND warm-dispatch failures alike fall back to the
            # step-at-a-time replay: the queued payloads are intact host-side
            # and MUST apply (their update_counts already advanced at enqueue
            # — raising here would silently lose up to K-1 steps of data).
            # classify_and_demote keeps transient faults retryable under the
            # PR-7 budget and demotes structural/persistent ones.
            classified = _txn.classify_and_demote(
                self._cache, _FALLBACK, self._transient_fails, key, exc
            )
            if isinstance(exc, _Ineligible):
                st.fallback(f"scan-ineligible:{exc}")
            elif not first:
                st.fallback(f"scan-warm-dispatch-failed:{classified or type(exc).__name__}")
            else:
                st.fallback(
                    f"scan-dispatch-{classified}" if classified else f"scan-trace-failed:{type(exc).__name__}"
                )
            return False

        if first:
            st.traces += 1
            self._cache[key] = entry
            fp = self._fingerprint(state_sig, kb, device, work.qkey)
            cause = _diag.attribute_retrace(fp, list(self._fingerprints.values()))
            self._fingerprints[key] = fp
            if cause != "initial":
                st.retrace_causes[cause] += 1
            if rec is not None:
                rec.record(
                    "update.scan.trace" if cause == "initial" else "update.scan.retrace",
                    st.owner, cause=cause, k_bucket=kb, signatures=len(self._fingerprints),
                )
        else:
            st.cache_hits += 1
        st.dispatches += 1
        st.scan_dispatches += 1
        st.scan_steps_folded += n
        st.scan_pad_steps += pad
        if donate:
            st.donated_dispatches += 1
        else:
            st.donation_fallbacks += 1
        bytes_moved = state_bytes + step_in_bytes * kb
        st.bytes_moved += bytes_moved
        dispatch_us = round((perf_counter() - t_dispatch) * 1e6, 3) if measuring else 0.0
        if measuring:
            _hist.observe(st.owner, "scan", "dispatch_us", dispatch_us)
        device_us = None
        if profiling and not first:
            device_us = completion_probe(out, st.owner, "scan", st, t_dispatch)
        if rec is not None:
            span = {} if work.lineage is None else {"lineage": work.lineage}
            rec.record(
                "update.scan", st.owner,
                dispatch_us=dispatch_us, steps=n, k=work.k, k_bucket=kb,
                pad_steps=pad, bytes=bytes_moved, donated=donate,
                cached=not first, reason=work.reason, **span,
            )
            if device_us is not None:
                rec.record("update.scan.probe", st.owner, dispatch_us=dispatch_us, device_us=device_us)
        self._writeback(out, n, profiling and not first, work.names)
        return True

    # -- async tier (engine/async_dispatch.py) ---------------------------

    def _submit_work(self, work: _DrainWork) -> None:
        """Hand a swapped buffer to the background worker, under backpressure.

        At most ``_async_limit`` buffers may be pending behind the worker; a
        caller that outruns the drain blocks on the OLDEST buffer instead of
        growing host memory without bound. A poisoned queue (a prior drain
        failed) short-circuits to the caller-side FIFO replay.
        """
        from torchmetrics_tpu.engine import async_dispatch as _async

        st = self.stats
        with self._lock:
            limit = self._async_limit or 1
        # first drain of a (signature, K-bucket) pair COMPILES, and the trace
        # diffs the metric's __dict__ — which the caller's next enqueues
        # mutate concurrently (_update_count/_computed bookkeeping). Compiles
        # therefore run HERE on the caller, race-free; only warm dispatches of
        # the cached executable ride the worker. The prediction below can race
        # an in-flight drain's writeback (the x64 first-update widening moves
        # the state signature) — a mispredicted warm submit is still safe:
        # the worker refuses to compile (allow_compile=False) and hands the
        # buffer back for a caller-side replay instead.
        gathered = self._gather_state(work.names)
        key = None
        if gathered is not None:
            key = (work.qkey, gathered[1], gathered[2], k_bucket(len(work.pending)))
        # tmlint: disable=TM601 — documented racy prediction: a concurrent
        # worker may demote this key under the drain mutex, but a misprediction
        # is safe either way (the worker refuses to compile and hands back)
        if key is None or key not in self._cache:
            # the work item already rides the in-flight FIFO (appended at the
            # swap), so wait out the OLDER items only — waiting on ourselves
            # would deadlock — then settle any handed-back payloads first
            self._join_until(work)
            try:
                with self._drain_mutex:
                    ok = self._execute_work(work)
                if not ok:
                    self._replay(work.pending, work.names)
                    work.replay = True  # joiners must not count it again
                self._post_drain()
            finally:
                work.done.set()
            return
        while True:
            with self._lock:
                while (
                    self._inflight
                    and self._inflight[0].done.is_set()
                    and self._inflight[0] is not work
                ):
                    self._inflight.popleft()
                # the bound counts OUR buffer too (it joined the FIFO at swap):
                # more than `limit` tracked buffers = wait on the oldest, which
                # is never ours (ours is the newest)
                oldest = self._inflight[0] if len(self._inflight) > limit else None
                poisoned = self._poisoned
            if poisoned:
                # worker is handing payloads back: settle everything in FIFO
                # order on THIS thread, this buffer included
                with self._lock:
                    try:
                        self._inflight.remove(work)
                    except ValueError:
                        pass
                self.join_async("async-poisoned")
                self._replay(work.pending, work.names)
                st.async_replayed_steps += len(work.pending)
                self._post_drain()
                work.replay = True
                work.done.set()
                return
            if oldest is None or oldest is work:
                break
            st.async_backpressure_waits += 1
            if oldest.first_wait_t is None:
                oldest.first_wait_t = perf_counter()
            oldest.done.wait()
        with self._lock:
            depth = len(self._inflight)
        st.async_submits += 1
        rec = _diag.active_recorder()
        if rec is not None or _profile.active_profile() is not None:
            # queue-depth distribution: how far the caller runs ahead of the
            # drain (1 = pure double buffering, `limit` = backpressure ceiling)
            _hist.observe(st.owner, "async", "depth", float(depth))
            if rec is not None:
                span = {} if work.lineage is None else {"lineage": work.lineage}
                rec.record(
                    "async.enqueue", st.owner,
                    steps=len(work.pending), depth=depth, reason=work.reason, **span,
                )
        _async.submit(work)

    def _join_until(self, work: _DrainWork) -> None:
        """Wait out (and settle) everything swapped BEFORE ``work``."""
        while True:
            with self._lock:
                while (
                    self._inflight
                    and self._inflight[0].done.is_set()
                    and self._inflight[0] is not work
                ):
                    self._inflight.popleft()
                head = self._inflight[0] if self._inflight else None
            if head is None or head is work:
                break
            if head.first_wait_t is None:
                head.first_wait_t = perf_counter()
            head.done.wait()
        self._collect_failed()

    def _abandon(self, work: _DrainWork) -> None:
        """A buffer that can no longer reach the worker: route it to the
        failed FIFO (the next join replays it) and release its waiters."""
        if work.done.is_set():
            return
        with self._lock:
            work.replay = True
            self._failed.append(work)
        work.done.set()

    def _worker_execute(self, work: _DrainWork) -> None:
        """The background half of a drain (executor thread, submit context).

        Failure semantics differ from the sync path on purpose: the payload
        is handed BACK for the next caller-side join to replay — the hot loop
        never pays a replay, and the poisoned flag stops later buffers from
        dispatching ahead of the failed one. Success defers the view
        re-anchor to the join (the observer's thread), matching the contract
        that only observers read state.
        """
        st = self.stats
        with self._lock:
            if self._poisoned:
                # passthrough: joiners count it ONCE, at replay — checked and
                # appended in ONE critical section so a concurrent join cannot
                # clear the flag between the read and the hand-back (the
                # executor's finally sets work.done after we return)
                work.replay = True
                self._failed.append(work)
                return
        from torchmetrics_tpu.diag.transfer_guard import native_reentry

        t0 = perf_counter()
        try:
            with self._drain_mutex, native_reentry():
                ok = self._execute_work(work, allow_compile=False)
        except Exception as exc:  # noqa: BLE001 — donation consumed the state: raise at the join
            work.error = exc
            with self._lock:
                self._failed.append(work)
                self._poisoned = True
            return
        end = perf_counter()
        if not ok:
            work.replay = True
            with self._lock:
                self._failed.append(work)
                self._poisoned = True
            return
        exec_us = round((end - t0) * 1e6, 3)
        # overlap credit: the slice of this drain during which NO caller was
        # blocked on it — genuine caller forward progress behind the worker
        fw = work.first_wait_t
        overlap_us = round(max(0.0, ((min(fw, end) if fw is not None else end) - t0) * 1e6), 3)
        st.async_dispatches += 1
        st.async_overlap_us += int(overlap_us)
        with self._lock:
            self._post_pending = True
        rec = _diag.active_recorder()
        if rec is not None:
            span = {} if work.lineage is None else {"lineage": work.lineage}
            rec.record(
                "async.drain", st.owner,
                dispatch_us=exec_us, overlap_us=overlap_us,
                steps=len(work.pending), reason=work.reason, **span,
            )

    def join_async(self, reason: str, collect: bool = True) -> int:
        """Wait out this queue's in-flight background drains (the JOIN).

        Runs on the OBSERVER's thread: waits the FIFO dry, replays any
        payloads a failed drain handed back (unless ``collect=False`` — the
        discard path drops them instead), fires the deferred view re-anchor,
        and credits pending epoch-sync overlap windows. Returns the number of
        steps settled (completed + replayed).
        """
        settled = 0
        waited = False
        t0 = 0.0
        last_span: Optional[int] = None
        while True:
            with self._lock:
                while self._inflight and self._inflight[0].done.is_set():
                    self._inflight.popleft()
                work = self._inflight[0] if self._inflight else None
            if work is None:
                break
            if not waited:
                waited = True
                t0 = perf_counter()
            if work.first_wait_t is None:
                work.first_wait_t = perf_counter()
            work.done.wait()
            if not work.replay and work.error is None:
                # failed buffers count ONCE — at their replay in
                # _collect_failed below, not here
                settled += len(work.pending)
                if work.lineage is not None:
                    last_span = work.lineage
        st = self.stats
        if waited:
            wait_us = round((perf_counter() - t0) * 1e6, 3)
            st.async_joins += 1
            st.async_join_wait_us += int(wait_us)
            rec = _diag.active_recorder()
            if rec is not None:
                span = {} if last_span is None else {"lineage": last_span}
                rec.record("async.join", st.owner, reason=reason, steps=settled, wait_us=wait_us, **span)
        if collect:
            settled += self._collect_failed()
        with self._lock:
            post_pending, self._post_pending = self._post_pending, False
        if post_pending:
            self._post_drain()
        from torchmetrics_tpu.engine import async_dispatch as _async

        _async.consume_sync_notes()
        return settled

    def _collect_failed(self) -> int:
        """Replay handed-back payloads in FIFO order on THIS thread."""
        replayed = 0
        error = None
        while True:
            with self._lock:
                if not self._failed:
                    self._poisoned = False
                    break
                work = self._failed.popleft()
            if work.error is not None:
                # donation consumed the state mid-drain: data is genuinely
                # lost and the observer must know — the sync path raises the
                # same way
                error = work.error
                continue
            self._replay(work.pending, work.names)
            self.stats.async_replayed_steps += len(work.pending)
            replayed += len(work.pending)
        if replayed:
            self._post_drain()
        if error is not None:
            raise error
        return replayed

    def _prefetch(self, inputs):
        """``jax.device_put`` host arrays at ENQUEUE time (async mode only).

        The H2D staging is an asynchronous dispatch: it proceeds in the
        background while the caller keeps enqueueing, so the drain finds its
        payload already on device instead of staging it inside the step.
        """
        import jax

        out = list(inputs)
        staged = 0
        for i, x in enumerate(out):
            if isinstance(x, np.ndarray):
                out[i] = jax.device_put(x)
                staged += 1
        if staged:
            self.stats.async_prefetches += staged
            return out
        return inputs


class MetricScan(_ScanQueue):
    """The scan queue of one metric's :class:`CompiledUpdate` engine."""

    def __init__(self, engine) -> None:
        self._engine = engine
        #: (n_args, kw_names, raw_in_sig, bucketed, bucket, n_pad) of the last
        #: slow-path push — the fixed-shape-stream enqueue fast path
        self._fast: Optional[Tuple] = None
        super().__init__(engine.stats)

    def owns(self, metric: Any) -> bool:
        return metric is self._engine._metric

    def exclusive_to(self, metrics: Sequence[Any]) -> bool:
        return any(self._engine._metric is m for m in metrics)

    # tmlint: holds(_lock)
    def _push_locked(self, args, kwargs, k: int) -> bool:
        eng = self._engine
        st = self.stats
        m = eng._metric
        if kwargs:
            kw_names = tuple(sorted(kwargs))
            inputs = list(args) + [kwargs[kn] for kn in kw_names]
        else:
            kw_names = ()
            inputs = list(args)
        in_sig = input_signature(inputs)
        if in_sig is None:
            self._flush_point_locked("ineligible-step", asyncable=False)
            st.fallback("non-array-input")
            return False
        # fast path: a fixed-shape stream repeats one raw signature — skip the
        # bucket resolution and qkey rebuild the slow path below re-derives
        # (the enqueue side is the per-step cost the whole design amortizes)
        fast = self._fast
        if (
            fast is not None
            and self._pending
            and k == self._k
            and fast[0] == len(args)
            and fast[1] == kw_names
            and fast[2] == in_sig
        ):
            bucketed, bucket, n_pad = fast[3], fast[4], fast[5]
            if bucketed:
                st.bucketed_steps += 1
                st.bucket_pad_rows += n_pad
                if n_pad:
                    inputs = list(bucketing.pad_args(inputs, bucket))
            if self._async_limit:
                inputs = self._prefetch(inputs)
            self._pending.append((args, kwargs, tuple(inputs), n_pad))
            _lineage.note_enqueued(st.owner)
            if len(self._pending) >= k:
                self._flush_point_locked("k-reached", asyncable=True)
            return True
        if not self._pending:
            # state eligibility is a queue-start check: states cannot change
            # while payloads are queued (only drains write them)
            for name in m._defaults:
                if not _is_jax_array(getattr(m, name)):
                    st.fallback("non-array-state")
                    return False
        if eng._bucket_ok is None:
            eng._bucket_ok = bucketing.bucket_eligible(m)
        raw_sig = in_sig
        n_pad = 0
        bucket: Optional[int] = None
        bucketed = False
        if eng._bucket_ok and config.BUCKETING_ENABLED:
            nrows = bucketing.batch_size(inputs)
            if nrows is not None and nrows > 0:
                bucket = bucketing.next_bucket(nrows)
                n_pad = bucket - nrows
                if n_pad:  # exact-fit batches keep their signature as-is
                    inputs = list(bucketing.pad_args(inputs, bucket))
                    in_sig = input_signature(inputs)
                bucketed = True
                st.bucketed_steps += 1
                st.bucket_pad_rows += n_pad
                st.bucket_sizes.add(bucket)
        qkey = (bucketed, len(args), kw_names, in_sig, bucket)
        if self._pending and (qkey != self._qkey or k != self._k):
            self._flush_point_locked("signature-change", asyncable=True)
        self._qkey = qkey
        self._k = k
        self._fast = (len(args), kw_names, raw_sig, bucketed, bucket, n_pad)
        if self._async_limit:
            inputs = self._prefetch(inputs)
        self._pending.append((args, kwargs, tuple(inputs), n_pad))
        _lineage.note_enqueued(st.owner)
        if len(self._pending) >= k:
            self._flush_point_locked("k-reached", asyncable=True)
        return True

    def _gather_state(self, names):
        m = self._engine._metric
        state: Dict[str, Any] = {}
        for name in m._defaults:
            v = getattr(m, name)
            if not _is_jax_array(v):
                return None
            state[name] = v
        if _sentinel.sentinel_enabled():
            state[_sentinel.STATE_KEY] = _sentinel.ensure_flags(m)
        if _txn.quarantine_enabled():
            state[_txn.STATE_KEY] = _txn.ensure_count(m)
        if _numerics.compensation_active(m):
            state[_numerics.STATE_KEY] = _numerics.ensure_residuals(m)
        return state, state_signature(state), type(self._engine)._device_token(state)

    def _compile_entry(self, example_state, example_inputs, kb: int, key: Tuple, work: _DrainWork):
        m = self._engine._metric
        owner = self.stats.owner
        bucketed, n_args, kw_names = work.qkey[0], work.qkey[1], work.qkey[2]
        quarantined, comp_names, step_txn, step_comp = build_riders(m, example_inputs)
        run = build_run(m, owner, n_args, kw_names, quarantined, comp_names)
        body = make_step_body(run, bucketed, example_inputs, txn=step_txn, comp=step_comp)
        return compile_scan(body, example_state, example_inputs, kb, owner, key, self.stats)

    def _shield(self, state, names):
        return shield_state(state, self._engine._metric, self.stats)

    def _invalidated(self, names) -> bool:
        return state_invalidated(self._engine._metric)

    def _writeback(self, out, steps: int, probing: bool, names) -> None:
        m = self._engine._metric
        st = self.stats
        st.metrics_updated += steps
        write_member_state(m, out, steps, st)
        _lineage.note_folded(st.owner, steps)
        if probing:
            _numerics.maybe_drift_probe(m, st)

    def _replay(self, pending, names) -> None:
        """Step-at-a-time fallback: byte-identical order, counted, never lost."""
        eng = self._engine
        m = eng._metric
        for args, kwargs, _, _ in pending:
            if not eng.step(args, kwargs):
                m._run_eager_update(args, kwargs)
        # replayed steps DID apply (eagerly) — they advance the fold
        # watermark, but the record flags them: they skipped the attested
        # single-dispatch scan path
        _lineage.note_folded(self.stats.owner, len(pending))
        _lineage.note_excluded(self.stats.owner, "replayed", len(pending))

    def _fingerprint(self, state_sig, kb: int, device: str, qkey) -> Dict[str, Any]:
        bucketed, n_args, kw_names, in_sig, bucket = qkey
        # the K-bucket joins the bucket aspect so a ragged-tail recompile
        # attributes as bucket-miss, never as an uncaused retrace
        return signature_fingerprint((n_args, kw_names), state_sig, in_sig, (bucket, kb), device)


class FusedScan(_ScanQueue):
    """The scan queue of a collection's :class:`FusedUpdate` engine."""

    def __init__(self, engine) -> None:
        self._engine = engine
        super().__init__(engine.stats)
        self._probed: Dict[Tuple, FrozenSet[str]] = {}  # qkey -> fusable member names
        self._names: FrozenSet[str] = frozenset()

    def owns(self, metric: Any) -> bool:
        return any(m is metric for _, m in self._engine.metrics)

    def exclusive_to(self, metrics: Sequence[Any]) -> bool:
        # the queued payloads fold into the PROBED member set; every one of
        # those members must be covered for a discard to lose nothing
        covered = [m for _, m in self._members(self._names)]
        return all(any(m is c for c in metrics) for m in covered)

    # tmlint: holds(_lock)
    def _push_locked(self, args, kwargs, k: int) -> Optional[Set[str]]:
        eng = self._engine
        st = self.stats
        if kwargs:
            self._flush_point_locked("ineligible-step", asyncable=False)
            st.fallback("kwargs")
            return None
        inputs = list(args)
        in_sig = input_signature(inputs)
        if in_sig is None:
            self._flush_point_locked("ineligible-step", asyncable=False)
            st.fallback("non-array-input")
            return None
        members = eng.eligible_members(check_arrays=not self._pending)
        if len(members) < 2:
            self._flush_point_locked("ineligible-step", asyncable=False)
            st.fallback("too-few-members")
            return None
        n_pad = 0
        bucket: Optional[int] = None
        bucketed = False
        if config.BUCKETING_ENABLED and all(bucketing.bucket_eligible(m) for _, m in members):
            nrows = bucketing.batch_size(inputs)
            if nrows is not None and nrows > 0:
                bucket = bucketing.next_bucket(nrows)
                n_pad = bucket - nrows
                inputs = list(bucketing.pad_args(inputs, bucket))
                in_sig = input_signature(inputs)
                bucketed = True
                st.bucketed_steps += 1
                st.bucket_pad_rows += n_pad
                st.bucket_sizes.add(bucket)
        qkey = (bucketed, in_sig, bucket, tuple(name for name, _ in members))
        fused_names = self._probed.get(qkey)
        if fused_names is None:
            # one abstract trace probe per signature decides membership BEFORE
            # anything queues — the handled set must be exact at enqueue time
            from torchmetrics_tpu.engine.fusion import probe_fusable

            states = {name: {sn: getattr(m, sn) for sn in m._defaults} for name, m in members}
            fused_names = probe_fusable(members, states, inputs, st)
            self._probed[qkey] = fused_names
        if len(fused_names) < 2:
            self._flush_point_locked("ineligible-step", asyncable=False)
            st.fallback("too-few-traceable-members")
            return None
        if self._pending and (qkey != self._qkey or k != self._k):
            self._flush_point_locked("signature-change", asyncable=True)
        self._qkey = qkey
        self._k = k
        self._names = fused_names
        if self._async_limit:
            inputs = self._prefetch(inputs)
        self._pending.append((args, {}, tuple(inputs), n_pad))
        # the host-side bookkeeping the one-step fused writeback would do,
        # done at ENQUEUE: update_count is observation-independent (any state
        # read drains first), and _computed must invalidate immediately
        handled: Set[str] = set()
        for name, m in members:
            if name in fused_names:
                m._computed = None
                m._update_count += 1
                handled.add(name)
                # per-member watermark (observation sites key by type name);
                # the causal span lives on the QUEUE owner, opened below
                _lineage.note_enqueued(type(m).__name__, span=False)
        _lineage.open_span(st.owner)
        if len(self._pending) >= k:
            self._flush_point_locked("k-reached", asyncable=True)
        return handled

    def _members(self, names) -> List[Tuple[str, Any]]:
        return [(name, m) for name, m in self._engine.metrics if name in names]

    def _names_snapshot(self):
        return self._names

    def _gather_state(self, names):
        states: Dict[str, Dict[str, Any]] = {}
        sigs = []
        device = ""
        for name, m in self._members(names):
            mstate = {sn: getattr(m, sn) for sn in m._defaults}
            if not all(_is_jax_array(v) for v in mstate.values()):
                return None
            if _sentinel.sentinel_enabled():
                mstate[_sentinel.STATE_KEY] = _sentinel.ensure_flags(m)
            if _txn.quarantine_enabled():
                mstate[_txn.STATE_KEY] = _txn.ensure_count(m)
            if _numerics.compensation_active(m):
                mstate[_numerics.STATE_KEY] = _numerics.ensure_residuals(m)
            states[name] = mstate
            sigs.append((name, state_signature(mstate)))
            if not device:
                from torchmetrics_tpu.engine.compiled import CompiledUpdate

                device = CompiledUpdate._device_token(mstate)
        return states, tuple(sigs), device

    def _compile_entry(self, example_state, example_inputs, kb: int, key: Tuple, work: _DrainWork):
        from torchmetrics_tpu.engine.fusion import build_fused_riders, build_run_all

        bucketed = work.qkey[0]
        fusable = self._members(work.names)
        quarantined, comp_names, step_txn, step_comp = build_fused_riders(fusable, example_inputs)
        run_all = build_run_all(fusable, comp_names, quarantined)
        body = make_step_body(run_all, bucketed, example_inputs, txn=step_txn, comp=step_comp)
        return compile_scan(body, example_state, example_inputs, kb, self.stats.owner, key, self.stats)

    def _shield(self, states, names):
        return {name: shield_state(states[name], m, self.stats) for name, m in self._members(names)}

    def _invalidated(self, names) -> bool:
        return any(state_invalidated(m) for _, m in self._members(names))

    def _writeback(self, out, steps: int, probing: bool, names) -> None:
        st = self.stats
        for name, m in self._members(names):
            st.metrics_updated += steps
            residual_out = write_member_state(m, out[name], steps, st)
            _lineage.note_folded(type(m).__name__, steps)
            if probing and residual_out is not None:
                _numerics.maybe_drift_probe(m, st, owner=f"{st.owner}:{name}")

    def _replay(self, pending, names) -> None:
        """Per-member eager replay (update_count already advanced at enqueue)."""
        for args, _, _, _ in pending:
            for _, m in self._members(names):
                m._run_eager_update(args, {})
        for _, m in self._members(names):
            _lineage.note_folded(type(m).__name__, len(pending))
            _lineage.note_excluded(type(m).__name__, "replayed", len(pending))

    def _note_discarded(self, names, steps: int) -> None:
        for _, m in self._members(names):
            _lineage.note_discarded(type(m).__name__, steps)

    def _fingerprint(self, state_sig, kb: int, device: str, qkey) -> Dict[str, Any]:
        bucketed, in_sig, bucket, _ = qkey
        fp = type(self._engine)._fingerprint(state_sig, in_sig, (bucket, kb))
        fp["device"] = device
        return fp

    def _post_drain(self) -> None:
        cb = getattr(self._engine, "on_scan_drain", None)
        if cb is not None:
            # a drain donated the owners' buffers: the owning collection
            # re-anchors its group views NOW, not at the next accessor
            cb()
