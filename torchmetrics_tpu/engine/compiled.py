"""Per-metric compiled-step cache with donated state buffers.

A metric's ``update`` mutates ``self.<state>`` attributes. The engine re-expresses
one update call as a pure function ``state_pytree -> state_pytree`` by swapping
traced state values onto the metric, running the original update body, and
collecting the resulting attributes — then compiles that function once per
``(state treedef, input shapes/dtypes)`` signature with ``donate_argnums=(0,)``
so XLA reuses the old state buffers for the new state in place (the pjit
donation pattern). Steady state is ONE cached dispatch per step: no Python
re-trace, no per-op dispatch, no state copy.

Anything that cannot trace — list states, non-array inputs, value-dependent
host validation, side effects on non-state attributes — falls back to the
eager path and is counted in :class:`EngineStats`, never silently dropped.

Donation safety: a donated buffer is dead after dispatch, so leaves that are
also referenced OUTSIDE the state slot (the registered defaults that
``reset()`` restores, a ``sync()`` snapshot in ``_cache``, a cached
``compute()`` result the user may still hold) are copied first. The copy shows
up as ``donation_copies`` and only ever happens on the first step after a
reset/compute — steady-state steps donate without copying.
"""

from __future__ import annotations

import zlib
from time import perf_counter
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu.diag import costs as _costs
from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import profile as _profile
from torchmetrics_tpu.diag import sentinel as _sentinel
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.engine import bucketing, config
from torchmetrics_tpu.engine import numerics as _numerics
from torchmetrics_tpu.engine import persist as _persist
from torchmetrics_tpu.engine import statespec as _statespec
from torchmetrics_tpu.engine import txn as _txn
from torchmetrics_tpu.engine.stats import EngineStats


def annotation_scope(owner: str, kind: str, key: Any) -> str:
    """The ``tm:<owner>:<kind>:<signature>`` name a dispatch is annotated with.

    Shared by every engine: the same string wraps the host-side dispatch
    (``jax.profiler.TraceAnnotation``) so a native XLA/Perfetto profile
    attributes device slices to the owning metric's compiled graph. Computed
    once per compile (the signature digest is stable per cache entry) and
    cached alongside the executable — the hot loop pays one string reuse.
    """
    digest = format(zlib.crc32(repr(key).encode()) & 0xFFFFFFFF, "08x")
    return f"tm:{owner}:{kind}:{digest}"


def completion_probe(out: Any, owner: str, kind: str, stats: EngineStats, t_dispatch: float) -> Optional[float]:
    """Sampled completion probe: block on every Nth warm dispatch's outputs.

    Returns the measured ``device_us`` (dispatch start → results ready) when
    this dispatch was sampled, else None. The block runs inside
    ``transfer_allowed`` — waiting for completion is the declared, sanctioned
    way to observe device time; unsampled steps remain untouched so the
    strict transfer guard holds exactly as without profiling.
    """
    if not _profile.probe_due(owner, kind):
        return None
    import jax

    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    t_block = perf_counter()
    with transfer_allowed("profile-probe"):
        jax.block_until_ready(out)
    t_done = perf_counter()
    device_us = round((t_done - t_dispatch) * 1e6, 3)
    stats.profile_probes += 1
    # the probe's OVERHEAD is only the blocking wait — the dispatch itself
    # happened regardless; this is what the analytic < 2% CI bound multiplies
    # by the sampling rate
    _profile.note_probe(owner, kind, round((t_done - t_block) * 1e6, 3))
    _hist.observe(owner, kind, "device_us", device_us)
    return device_us

_FALLBACK = object()  # cache sentinel: this signature is known-uncompilable


def signature_fingerprint(
    treedef: Tuple, state_sig: Tuple, in_sig: Tuple, bucket: Optional[int], device: str
) -> Dict[str, Any]:
    """Structured digest of a compile signature for retrace-cause attribution.

    Splits the flat cache key into the aspects a retrace can be blamed on —
    pytree structure, dtypes, shapes, shape bucket, device — so
    :func:`torchmetrics_tpu.diag.trace.attribute_retrace` can diff a new
    signature against previously compiled ones and name the minimal change
    (``bucket-miss`` vs ``dtype-change`` vs ``treedef-change`` …).
    ``state_sig`` entries are ``(name, shape, dtype)`` — or, for nested riders
    like the compensation residual, ``(name, ((sub, shape, dtype), ...))`` —
    and ``in_sig`` entries are ``(shape, dtype)``.
    """
    names, dtypes, shapes = [], [], []
    for entry in state_sig:
        if len(entry) == 2:  # nested rider: (key, ((sub, shape, dtype), ...))
            names.append((entry[0], tuple(n for n, _, _ in entry[1])))
            dtypes.extend(d for _, _, d in entry[1])
            shapes.extend(s for _, s, _ in entry[1])
        else:
            names.append(entry[0])
            shapes.append(entry[1])
            dtypes.append(entry[2])
    return {
        "treedef": (treedef, tuple(names)),
        "dtype": (tuple(dtypes), tuple(d for _, d in in_sig)),
        "shape": (tuple(shapes), tuple(s for s, _ in in_sig)),
        "bucket": bucket,
        "device": device,
    }


class _Ineligible(Exception):
    """Raised inside a trace to abort compilation with a recorded reason."""


#: resolved once — `jax.Array`/`jax.core.Tracer` attribute walks go through
#: jax's lazy-module `__getattr__` machinery, which costs ~µs per access and
#: sits on the per-step enqueue fast path (input_signature is rebuilt every
#: warm step; the scan/async enqueue cost IS the product)
_ARRAY_TYPES: Optional[tuple] = None
_TRACER_CLS: Any = None


def _array_types() -> tuple:
    global _ARRAY_TYPES, _TRACER_CLS
    if _ARRAY_TYPES is None:
        import jax
        import jax.numpy as jnp

        _ARRAY_TYPES = (jax.Array, jnp.ndarray)
        _TRACER_CLS = jax.core.Tracer
    return _ARRAY_TYPES


def _is_jax_array(x: Any) -> bool:
    types = _ARRAY_TYPES if _ARRAY_TYPES is not None else _array_types()
    return isinstance(x, types) and not isinstance(x, (list, tuple))


def _is_metric_like(x: Any) -> bool:
    # duck-typed (no Metric import — engine must stay import-acyclic with metric.py)
    return hasattr(x, "_defaults") and hasattr(x, "update") and hasattr(x, "compute")


def holds_nested_metrics(metric: Any) -> bool:
    """True when ``metric`` owns inner Metric objects (wrappers, compositions).

    Tracing such an update would run the INNER metrics' stateful host machinery
    once at trace time and assign tracer values to their states — a silent
    corruption the per-attribute side-effect check cannot see (the inner object
    identity never changes). Wrappers therefore always run eagerly; their inner
    metrics' own engines still compile the actual work.

    Exemption: a wrapper that uses an inner metric ONLY as a traced body under
    the :func:`traced_update` snapshot/restore hygiene (the inner object's
    ``__dict__`` is restored wholesale before the trace ends, so no tracer can
    leak onto its live state) names that attribute in
    ``_engine_traced_bodies`` and stays engine-eligible — the ``serve/``
    streaming wrappers are the current holders of that contract. The
    exemption is PER ATTRIBUTE, never class-wide: any OTHER nested metric on
    the same object still disqualifies it (the corruption class this scan
    guards is unchanged for undeclared attributes).
    """
    exempt = getattr(metric, "_engine_traced_bodies", ())
    for k, v in metric.__dict__.items():
        if k in exempt:
            continue
        if _is_metric_like(v):
            return True
        if isinstance(v, (list, tuple)) and any(_is_metric_like(x) for x in v):
            return True
        if isinstance(v, dict) and any(_is_metric_like(x) for x in v.values()):
            return True
    return False


def traced_update(metric: Any, state: Dict[str, Any], args: Sequence[Any], kwargs: Dict[str, Any]) -> Dict[str, Any]:
    """Run ``metric``'s original update as ``state -> state`` (trace-safe).

    The metric's ``__dict__`` is snapshotted and restored wholesale, so a trace
    can never leak tracer values onto the live object. An update with side
    effects a compiled step would lose — rebinding a non-state attribute, or
    growing/shrinking a mutable one in place (``self.seen.append(...)``) —
    aborts compilation via :class:`_Ineligible` instead of silently diverging.
    """
    names = tuple(metric._defaults)
    snapshot = dict(metric.__dict__)
    # shallow content copies of mutable non-state containers: an in-place
    # mutation during an aborted trace must be rolled back, or the eager
    # fallback would re-run it and double the side effect
    containers = {
        k: (list(v) if isinstance(v, list) else dict(v) if isinstance(v, dict) else set(v))
        for k, v in snapshot.items()
        if k not in names and isinstance(v, (list, dict, set))
    }
    try:
        for k in names:
            object.__setattr__(metric, k, state[k])
        metric._raw_update(*args, **kwargs)
        out = {k: getattr(metric, k) for k in names}
        for k, v in metric.__dict__.items():
            if k in names:
                continue
            if snapshot.get(k, _FALLBACK) is not v:
                raise _Ineligible(f"update writes non-state attribute {k!r}")
            if k in containers and _container_changed(v, containers[k]):
                raise _Ineligible(f"update mutates non-state container {k!r} in place")
        return out
    finally:
        metric.__dict__.clear()
        metric.__dict__.update(snapshot)
        for k, saved in containers.items():
            live = snapshot[k]
            if _container_changed(live, saved):
                if isinstance(live, list):
                    live[:] = saved
                else:  # dict and set both restore via clear + update
                    live.clear()
                    live.update(saved)


def _container_changed(live: Any, saved: Any) -> bool:
    """Shallow in-place change detection by length + element IDENTITY.

    ``==`` would recurse into element values (arrays raise on bool coercion);
    identity comparison catches the realistic mutations — append/pop, dict
    value overwrite, set add/remove — without touching element semantics.
    """
    if len(live) != len(saved):
        return True
    if isinstance(live, list):
        return any(a is not b for a, b in zip(live, saved))
    if isinstance(live, dict):
        return live.keys() != saved.keys() or any(live[k] is not saved[k] for k in saved)
    return live != saved  # sets hold hashables only; equality is safe


def protected_ids(metric: Any) -> set:
    """ids of arrays that outlive the state slot and must not be donated."""
    import jax

    ids = set()
    for v in metric._defaults.values():
        if not isinstance(v, list):
            ids.add(id(v))
    if getattr(metric, "_cache", None):
        for leaf in jax.tree_util.tree_leaves(metric._cache):
            ids.add(id(leaf))
    if getattr(metric, "_computed", None) is not None:
        for leaf in jax.tree_util.tree_leaves(metric._computed):
            ids.add(id(leaf))
    return ids


def shield_state(state: Dict[str, Any], metric: Any, stats: EngineStats) -> Dict[str, Any]:
    """Copy state leaves whose buffers are aliased outside the state slot."""
    import jax.numpy as jnp

    shared = protected_ids(metric)

    def shield(v: Any) -> Any:
        if isinstance(v, dict):  # nested rider (the compensation residual dict)
            return {n: shield(x) for n, x in v.items()}
        if id(v) in shared:
            stats.donation_copies += 1
            return jnp.array(v, copy=True)
        return v

    return {k: shield(v) for k, v in state.items()}


def state_invalidated(metric: Any) -> bool:
    """Whether any live state leaf is a donation-consumed (deleted) jax array.

    A first execution that fails AFTER its dispatch donated the state pytree
    leaves the metric's attrs pointing at dead buffers — no fallback (ladder
    chunks, eager re-run) can read them, so the callers fail loud instead.
    """
    for k in getattr(metric, "_defaults", {}):
        v = getattr(metric, k, None)
        is_deleted = getattr(v, "is_deleted", None)
        if callable(is_deleted):
            try:
                if is_deleted():
                    return True
            except Exception:  # noqa: BLE001 — an unreadable buffer is a dead buffer
                return True
    return False


def make_step_body(run, bucketed: bool, inputs: Sequence[Any], txn=None, comp=None):
    """The un-jitted per-step composition ``(state, n_pad, flat) -> state``.

    Shared by :func:`make_step` (one step per dispatch) and the multi-step
    scan drain (``engine/scan.py``, which runs this body once per ``lax.scan``
    step over the queued axis) — the pad-subtract identity and the rider
    ordering (pad-subtract → compensation → quarantine transaction) live HERE,
    once. ``n_pad`` is ignored when ``bucketed`` is False.

    ``comp`` is the optional compensated-accumulation recomposition
    (``engine/numerics.py``), ``(old_state, result, flat) -> result``, applied
    after the pad-subtract identity: compensated entries of ``result`` hold the
    pure batch contribution (the run body zeroed those states), pad rows are
    already subtracted from it, and the two-sum then folds contribution +
    residual into the preserved old value.

    ``txn`` is the optional quarantine transaction (``engine/txn.py``),
    ``(old_state, result, flat) -> result``, applied LAST — after pad-subtract
    and compensation — so a poisoned batch selects back to the exact
    pre-update values (value AND residual alike; padding already removed from
    the rejected candidate, never from the preserved old state).
    """
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.engine import bucketing

    pad_rows = bucketing.pad_row_constants(inputs) if bucketed else ()

    def body(state, n_pad, flat):
        out = run(state, flat)
        if bucketed:
            zeros = jax.tree_util.tree_map(jnp.zeros_like, state)
            # per-pad-row contribution: constant zero rows for batched inputs,
            # the live traced value for non-batched ones
            unit_flat = [c if c is not None else flat[i] for i, c in enumerate(pad_rows)]
            unit = run(zeros, unit_flat)

            def subtract(path, o, u):
                # the rider roles (sentinel bitmask, quarantine counter,
                # compensation residual — statespec.PAD_EXEMPT_KEYS) are not
                # row-additive: pad rows cannot raise health flags, poison a
                # batch, or carry rounding error (they are zeros), so the
                # riders pass through the pad-subtract identity untouched
                if any(
                    getattr(p, "key", None) in _statespec.PAD_EXEMPT_KEYS for p in path
                ):
                    return o
                return o - u * n_pad.astype(o.dtype)

            result = jax.tree_util.tree_map_with_path(subtract, out, unit)
        else:
            result = out
        if comp is not None:
            result = comp(state, result, flat)
        return txn(state, result, flat) if txn is not None else result

    return body


def make_step(run, bucketed: bool, inputs: Sequence[Any], txn=None, comp=None, out_shardings=None):
    """Compile ``run(state_pytree, flat_inputs) -> state_pytree`` into a jitted
    step with the state pytree donated (policy permitting).

    Shared by the per-metric and the fused engines; the composition itself is
    :func:`make_step_body` (also the scan drain's per-step body). ``tree_map``
    keeps it agnostic to whether the state pytree is one metric's dict or a
    fused dict-of-dicts.

    ``out_shardings`` (``parallel/sharding.state_out_shardings`` over the
    example state, or ``None``) pins partitioned state leaves to their
    ``NamedSharding`` so the executable lowers as an SPMD program — the
    committed sharded inputs drive ``in_shardings`` by propagation, the
    output constraint keeps the new state sharded in place, and GSPMD
    inserts the in-graph ``psum``/``psum_scatter`` the partitioning needs.
    """
    import jax

    from torchmetrics_tpu.engine import config

    body = make_step_body(run, bucketed, inputs, txn=txn, comp=comp)

    if bucketed:

        def step(state, n_pad, *flat):
            return body(state, n_pad, flat)

    else:

        def step(state, *flat):
            return body(state, None, flat)

    donate = config.donation_enabled()
    jit_kwargs: Dict[str, Any] = {"donate_argnums": (0,) if donate else ()}
    if out_shardings is not None:
        jit_kwargs["out_shardings"] = out_shardings
    return jax.jit(step, **jit_kwargs), donate


def build_run(m: Any, owner: str, n_args: int, kw_names: Tuple[str, ...], quarantined: bool, comp_names: Tuple[str, ...]):
    """The traced update body ``run(state, flat) -> state`` for one metric.

    Factored out of :meth:`CompiledUpdate._compile` so the multi-step scan
    drain (``engine/scan.py``) composes the IDENTICAL graph per queued step —
    rider handling (sentinel fold placement, quarantine counter passthrough,
    zeroed compensated states) included.
    """
    import jax

    def run(state, flat):
        import jax.numpy as jnp

        state = dict(state)
        sentinel = state.pop(_sentinel.STATE_KEY, None)
        qcount = state.pop(_txn.STATE_KEY, None)
        residuals = state.pop(_numerics.STATE_KEY, None)
        if residuals is not None:
            # compensated states enter the update body ZEROED: the body
            # then leaves the pure batch contribution behind, and the
            # two-sum recomposition in make_step folds it into the
            # preserved old value with the exact error term
            state = {
                k: jnp.zeros_like(v) if k in comp_names else v for k, v in state.items()
            }
        call_args = tuple(flat[:n_args])
        call_kwargs = dict(zip(kw_names, flat[n_args:]))
        # named_scope is trace-time only: the HLO ops of this update body
        # carry the owner's name, so device profiles attribute their slices
        with jax.named_scope(f"{owner}:update"):
            out = traced_update(m, state, call_args, call_kwargs)
        if sentinel is not None:
            # with the quarantine transaction active the health checks fold
            # over the SELECTED (post-transaction) states instead — a
            # quarantined NaN input must not raise the nan bit on a state
            # that stayed clean; under compensation the body only saw
            # ZEROED copies, so the fold moves into the recomposition
            # (build_compensation) where the real accumulators exist
            out[_sentinel.STATE_KEY] = (
                sentinel
                if quarantined or residuals is not None
                else _sentinel.update_flags(sentinel, out, m)
            )
        if qcount is not None:
            out[_txn.STATE_KEY] = qcount
        if residuals is not None:
            out[_numerics.STATE_KEY] = residuals  # passthrough; folded in make_step
        return out

    return run


def build_riders(m: Any, inputs: Sequence[Any]):
    """``(quarantined, comp_names, step_txn, step_comp)`` for the active rider config.

    One planning site for the quarantine admission + transaction and the
    compensated recomposition closures, shared by the one-step compile and the
    scan drain so the composition can never drift between them.
    """
    quarantined = _txn.quarantine_enabled()
    comp_names = _numerics.comp_state_names(m) if _numerics.compensation_active(m) else ()
    admission = _txn.build_admission(m, inputs) if quarantined else None
    step_txn = None
    if quarantined:

        def step_txn(old_state, result, flat):
            return _txn.transact(m, old_state, result, admission(flat))

    step_comp = (
        _numerics.build_compensation(m, comp_names, admission=admission)
        if comp_names
        else None
    )
    return quarantined, comp_names, step_txn, step_comp


def state_signature(state: Dict[str, Any]) -> Tuple:
    """Shape/dtype cache key over a state dict whose riders may nest one level.

    The compensation residual (``numerics.STATE_KEY``) is a dict of arrays —
    its signature entry nests the per-state (name, shape, dtype) triples so a
    residual joining/leaving (or a compensated state reshaping) keys a fresh
    compile exactly like any other state change.
    """
    return tuple(
        (k, tuple(sorted((n, tuple(x.shape), x.dtype) for n, x in v.items())))
        if isinstance(v, dict)
        else (k, tuple(v.shape), v.dtype)
        for k, v in state.items()
    )


def input_signature(inputs: Sequence[Any]) -> Optional[Tuple]:
    """Shape/dtype key for the inputs, or None when something is not an array.

    Tracers are rejected: an update already executing under someone else's
    trace (a user-jitted step) must keep the pre-engine eager semantics — the
    engine only owns dispatches it issues from host level.
    """
    if _ARRAY_TYPES is None:
        _array_types()
    tracer = _TRACER_CLS
    sig = []
    for a in inputs:
        if isinstance(a, tracer):
            return None
        if _is_jax_array(a) or isinstance(a, np.ndarray):
            # dtype OBJECT, not str(dtype): numpy re-derives the name string on
            # every call and this key is rebuilt on every warm step
            sig.append((tuple(a.shape), a.dtype))
        else:
            return None
    return tuple(sig)


def _nbytes(x: Any) -> int:
    return getattr(x, "nbytes", 0)


class CompiledUpdate:
    """Compiled-step cache for ONE metric instance.

    Created lazily by :meth:`Metric._engine_step` on the first engine-enabled
    update; excluded from pickling/cloning (executables are rebuilt per
    process/instance).
    """

    def __init__(self, metric: Any) -> None:
        self._metric = metric
        self._cache: Dict[Tuple, Any] = {}
        self._fingerprints: Dict[Tuple, Dict[str, Any]] = {}  # key -> signature fingerprint (retrace attribution)
        self._transient_fails: Dict[Tuple, int] = {}  # key -> classified-failure count (ladder budget)
        self.stats = EngineStats(type(metric).__name__)
        self._bucket_ok: Optional[bool] = None
        self._scan = None  # lazy multi-step queue (engine/scan.py)
        defaults = metric._defaults
        self._disabled_reason: Optional[str] = None
        if not defaults:
            self._disabled_reason = "stateless"
        elif any(isinstance(d, list) for d in defaults.values()):
            self._disabled_reason = "list-state"
        elif holds_nested_metrics(metric):
            self._disabled_reason = "nested-metric"

    # ------------------------------------------------------------------ scan

    def scan_step(
        self, args: Tuple[Any, ...], kwargs: Dict[str, Any], k: int, async_inflight: Optional[int] = None
    ) -> bool:
        """Queue one update payload for the K-folding scan drain.

        Returns True when the payload was queued (it folds into state at the
        next drain — K reached, signature change, or any state observation);
        False requests the eager fallback for THIS step, after draining any
        pending payloads so ordering is preserved. ``async_inflight`` routes
        full buffers to the background worker (``engine/async_dispatch.py``)
        with the given in-flight bound.
        """
        if self._disabled_reason is not None:
            self.stats.fallback(self._disabled_reason)
            return False
        if self._scan is None:
            from torchmetrics_tpu.engine.scan import MetricScan

            self._scan = MetricScan(self)
        return self._scan.push(args, kwargs, k, async_inflight)

    # ------------------------------------------------------------------ step

    def step(self, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> bool:
        """Try to run one update through a compiled executable.

        Returns True when the step was handled (states written back); False
        requests the eager fallback. Never raises for eligibility reasons.
        """
        st = self.stats
        if self._disabled_reason is not None:
            st.fallback(self._disabled_reason)
            return False
        m = self._metric

        state: Dict[str, Any] = {}
        for k in m._defaults:
            v = getattr(m, k)
            if not _is_jax_array(v):
                st.fallback("non-array-state")
                return False
            state[k] = v

        kw_names = tuple(sorted(kwargs))
        inputs = list(args) + [kwargs[k] for k in kw_names]
        in_sig = input_signature(inputs)
        if in_sig is None:
            st.fallback("non-array-input")
            return False

        # shape-bucket ragged batches for eligible (row-additive, sum-reduced) metrics
        if self._bucket_ok is None:
            self._bucket_ok = bucketing.bucket_eligible(m)
        n_pad = 0
        bucketed = False
        bucket: Optional[int] = None
        if self._bucket_ok and config.BUCKETING_ENABLED:
            n = bucketing.batch_size(inputs)
            if n is not None and n > 0:
                bucket = bucketing.next_bucket(n)
                n_pad = bucket - n
                inputs = list(bucketing.pad_args(inputs, bucket))
                in_sig = input_signature(inputs)
                bucketed = True
                st.bucketed_steps += 1
                st.bucket_pad_rows += n_pad
                st.bucket_sizes.add(bucket)

        # opt-in health sentinel: the int32 bitmask joins the state pytree so
        # the checks lower into the SAME executable as the update body
        if _sentinel.sentinel_enabled():
            state[_sentinel.STATE_KEY] = _sentinel.ensure_flags(m)
        # opt-in quarantine: the device counter joins the pytree so the
        # admission prelude + transactional select lower into the same graph
        if _txn.quarantine_enabled():
            state[_txn.STATE_KEY] = _txn.ensure_count(m)
        # opt-in compensated accumulation: the residual dict joins the pytree
        # so the two-sum recomposition lowers into the same donated graph
        if _numerics.compensation_active(m):
            state[_numerics.STATE_KEY] = _numerics.ensure_residuals(m)

        state_sig = state_signature(state)
        key = (bucketed, len(args), kw_names, state_sig, in_sig, self._device_token(state))

        entry = self._cache.get(key)
        if entry is _FALLBACK:
            st.fallback("uncompilable-signature")
            return False

        first = entry is None
        rec = _diag.active_recorder()
        profiling = _profile.active_profile() is not None
        measuring = rec is not None or profiling
        t_dispatch = perf_counter() if measuring else 0.0
        try:
            if first:
                # tracing (and the AOT cost-ledger compile) happens here, so a
                # trace failure lands in the same demote-to-eager handler the
                # lazy first dispatch used
                entry = self._compile(len(args), kw_names, bucketed, inputs, state, n_pad, key)
            fn, donate, scope, step_bytes = entry
            if donate:
                state = shield_state(state, m, st)
            if measuring:
                t_dispatch = perf_counter()
            import jax

            # device-time attribution: the host-side annotation names the async
            # dispatch in native jax.profiler / Perfetto traces, so the device
            # slices this executable produces attribute to owner:kind:signature
            with jax.profiler.TraceAnnotation(scope):
                if bucketed:
                    out = fn(state, np.int32(n_pad), *inputs)
                else:
                    out = fn(state, *inputs)
        except Exception as exc:  # noqa: BLE001 — any trace failure demotes to eager
            if not first:
                raise  # a cached executable failing on matching shapes is a real bug
            if state_invalidated(m):
                # the failure escaped AFTER donation consumed the live state
                # buffers: there is nothing intact to retry the batch against —
                # fail loud here rather than crash the ladder/eager rung on
                # deleted arrays a few frames later
                raise
            # budget charged whether or not the ladder rescues the step below —
            # a ladder success must not reset the recompile meter
            classified = _txn.classify_and_demote(
                self._cache, _FALLBACK, self._transient_fails, key, exc
            )
            if classified is not None and bucketed and bucket is not None:
                # fallback ladder rung 2: a transient backend failure (OOM on a
                # fresh bucket) retries the batch as next-smaller-bucket chunks
                if self._ladder_step(args, kwargs, bucket, classified):
                    return True
            if isinstance(exc, _Ineligible):
                reason = str(exc)
            elif classified is not None:
                reason = f"dispatch-{classified}"
            else:
                reason = f"trace-failed:{type(exc).__name__}"
            st.fallback(reason)
            return False

        if first:
            st.traces += 1
            self._cache[key] = entry
            # prewarm manifest: one row per compiled signature (specs only —
            # zero-filled replays re-bucket to the identical executable)
            _persist.record_compile(
                st.owner, "update",
                args=inputs[: len(args)], kw=dict(zip(kw_names, inputs[len(args):])),
                bucket=bucket,
            )
            fp = signature_fingerprint((len(args), kw_names), state_sig, in_sig, bucket, key[-1])
            cause = _diag.attribute_retrace(fp, list(self._fingerprints.values()))
            self._fingerprints[key] = fp
            if cause != "initial":
                st.retrace_causes[cause] += 1
            if rec is not None:
                rec.record(
                    "update.trace" if cause == "initial" else "update.retrace",
                    st.owner, cause=cause, bucket=bucket, signatures=len(self._fingerprints),
                )
        else:
            st.cache_hits += 1
        st.dispatches += 1
        st.metrics_updated += 1
        if donate:
            st.donated_dispatches += 1
        else:
            st.donation_fallbacks += 1
        # static per-signature byte count, computed once at compile time
        bytes_moved = step_bytes
        st.bytes_moved += bytes_moved
        dispatch_us = round((perf_counter() - t_dispatch) * 1e6, 3) if measuring else 0.0
        if measuring:
            _hist.observe(st.owner, "update", "dispatch_us", dispatch_us)
        # sampled completion probe (warm dispatches only: a cold dispatch's
        # wait includes compile residue and would poison the device-time tail)
        device_us = None
        if profiling and not first:
            device_us = completion_probe(list(out.values()), st.owner, "update", st, t_dispatch)
        if rec is not None:
            rec.record(
                "update.dispatch", st.owner,
                dispatch_us=dispatch_us,
                donated=donate, bucketed=bucketed, pad_rows=n_pad, bytes=bytes_moved, cached=not first,
            )
            if device_us is not None:
                rec.record("update.probe", st.owner, dispatch_us=dispatch_us, device_us=device_us)

        sentinel_out = out.pop(_sentinel.STATE_KEY, None)
        if sentinel_out is not None:
            setattr(m, _sentinel.ATTR, sentinel_out)
        quarantine_out = out.pop(_txn.STATE_KEY, None)
        if quarantine_out is not None:
            setattr(m, _txn.ATTR, quarantine_out)
        residual_out = out.pop(_numerics.STATE_KEY, None)
        if residual_out is not None:
            setattr(m, _numerics.ATTR, residual_out)
            st.compensated_steps += 1
        for k, v in out.items():
            setattr(m, k, v)
        if profiling and not first:
            # sampled precision-drift audit: every Nth warm dispatch reads the
            # (value, residual) pair at the sanctioned boundary — unsampled
            # steps stay byte-identical (the probe only reads)
            _numerics.maybe_drift_probe(m, st)
        return True

    # ------------------------------------------------------------------ ladder

    def _ladder_step(self, args: Tuple[Any, ...], kwargs: Dict[str, Any], bucket: int, classified: str) -> bool:
        """Fallback-ladder rung 2: retry the batch as half-bucket chunks.

        A dispatch-time resource failure at bucket ``b`` re-enters the SAME
        compiled machinery with the batch split at ``b/2`` — exact for the
        row-additive metrics bucketing admits (chunked accumulation commutes
        with the sum-reduced states). The first chunk's compile failing leaves
        state untouched (returns False → the caller's eager rung takes the
        whole batch); a residual chunk failing after the first applied runs
        eagerly HERE with quarantine parity, because the caller's eager path
        would re-apply rows the compiled chunks already accumulated.

        Under quarantine the FULL batch is admitted once before chunking:
        per-chunk admission would change the granularity of the contract (half
        a poisoned batch applied, the counter counting chunks) — this path is
        already exceptional, so one sanctioned read is the honest price.
        """
        half = bucket // 2
        if half < config.MIN_BUCKET:
            return False
        kw_names = tuple(sorted(kwargs))
        flat = list(args) + [kwargs[k] for k in kw_names]
        n = bucketing.batch_size(flat)
        if n is None or n <= half:
            return False
        st = self.stats
        m = self._metric
        if _txn.quarantine_enabled():
            import jax.numpy as jnp

            from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

            poisoned = _txn.build_admission(m, flat)(flat)
            with transfer_allowed("quarantine-check"):
                bad = bool(np.asarray(poisoned))
            if bad:
                setattr(m, _txn.ATTR, _txn.ensure_count(m) + jnp.int32(1))
                if _sentinel.sentinel_enabled():
                    setattr(
                        m, _sentinel.ATTR,
                        _sentinel.ensure_flags(m) | jnp.int32(_sentinel.FLAG_INPUT_POISONED),
                    )
                _diag.record(
                    "update.ladder", st.owner,
                    from_bucket=bucket, to_bucket=half, error=classified, rows=n, quarantined=True,
                )
                return True

        # the event narrates the ATTEMPTED walk (failed rungs included); the
        # counter below only counts a step-down that actually applied
        _diag.record(
            "update.ladder", st.owner,
            from_bucket=bucket, to_bucket=half, error=classified, rows=n,
        )

        def chunk(lo: int, hi: int) -> Tuple[Tuple[Any, ...], Dict[str, Any]]:
            sliced = [
                a[lo:hi] if getattr(a, "ndim", 0) >= 1 and a.shape[0] == n else a for a in flat
            ]
            return tuple(sliced[: len(args)]), dict(zip(kw_names, sliced[len(args):]))

        head_args, head_kwargs = chunk(0, half)
        if not self.step(head_args, head_kwargs):
            return False  # nothing applied — the whole batch goes eager upstream
        # counted only once something actually stepped down (the head chunk is
        # in): a failed ladder attempt must not claim a retry in the gates
        st.ladder_retries += 1
        rest_args, rest_kwargs = chunk(half, n)
        if not self.step(rest_args, rest_kwargs):
            # the head chunk is already folded in: the residue must run here
            _txn.eager_apply(self._metric, rest_args, rest_kwargs)
            st.fallback("ladder-eager-chunk")
        return True

    # ------------------------------------------------------------------ build

    def _compile(
        self,
        n_args: int,
        kw_names: Tuple[str, ...],
        bucketed: bool,
        inputs: Sequence[Any],
        example_state: Dict[str, Any],
        n_pad: int,
        key: Tuple,
    ):
        m = self._metric
        owner = self.stats.owner
        quarantined, comp_names, step_txn, step_comp = build_riders(m, inputs)
        run = build_run(m, owner, n_args, kw_names, quarantined, comp_names)
        from torchmetrics_tpu.parallel import sharding as _sharding

        fn, donate = make_step(
            run, bucketed, inputs, txn=step_txn, comp=step_comp,
            out_shardings=_sharding.state_out_shardings(example_state),
        )
        # ahead-of-time compile: same single trace+compile as the lazy first
        # dispatch, but the Compiled handle feeds the diag cost/memory ledger
        example = (example_state, np.int32(n_pad), *inputs) if bucketed else (example_state, *inputs)
        donated = sum(_nbytes(v) for v in example_state.values()) if donate else 0
        fn = _costs.aot_compile(
            fn, owner=owner, kind="update", args=example, donated_bytes=donated, stats=self.stats
        )
        step_bytes = sum(_nbytes(v) for v in example_state.values()) + sum(_nbytes(a) for a in inputs)
        return fn, donate, annotation_scope(owner, "update", key), step_bytes

    @staticmethod
    def _device_token(state: Dict[str, Any]) -> str:
        """Placement component of the cache key — ``to(device)`` must recompile.

        Sharding-aware (``parallel/sharding.placement_token``): partitioned
        leaves fold their ``PartitionSpec`` + device set into the token, so a
        re-placed state keys a fresh executable instead of dispatching one
        AOT-pinned to the old placement; single-device pytrees yield the bare
        device string the pre-sharding caches keyed on.
        """
        from torchmetrics_tpu.parallel.sharding import placement_token

        return placement_token(state)
