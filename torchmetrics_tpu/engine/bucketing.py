"""Shape buckets for ragged final batches.

An eval epoch whose last batch is short (or a stream of odd sizes) would force
one XLA trace per distinct batch size. Instead, inputs pad up to the next
power-of-two bucket, so the number of compiled variants is bounded by
``O(log2(max_batch))`` regardless of how ragged the stream is.

Correctness comes from a pad-subtract identity rather than per-metric masking
hooks: for a metric whose every state is SUM-reduced and whose ``update`` is
additive over batch rows (``new = old + Σ_r g(row_r)``), a pad row contributes a
fixed, state-independent delta ``g(pad_row)``. The compiled step therefore
computes, inside the SAME graph,

    out      = update(state, padded_inputs)            # includes pad garbage
    pad_unit = update(zeros_like(state), one_pad_row)  # = g(pad_row), a constant subgraph
    result   = out - n_pad * pad_unit

with ``n_pad`` a traced scalar — one executable serves every batch size in the
bucket, including the exact-fit case (``n_pad = 0``). Eligibility is explicit:
the metric class opts in with ``_engine_row_additive = True`` (the stat-scores
family, confusion matrices) AND every registered state must reduce with
``sum``; anything else skips bucketing and simply compiles per exact shape.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu.engine import config


def next_bucket(n: int, min_bucket: Optional[int] = None) -> int:
    """Smallest power-of-two bucket holding ``n`` rows (floored at ``MIN_BUCKET``).

    Example:
        >>> from torchmetrics_tpu.engine.bucketing import next_bucket
        >>> [next_bucket(n) for n in (1, 8, 9, 100)]
        [8, 8, 16, 128]
    """
    b = min_bucket if min_bucket is not None else config.MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def bucket_eligible(metric: Any) -> bool:
    """Whether ``metric`` supports the pad-subtract identity.

    Resolved from the registered :class:`~torchmetrics_tpu.engine.statespec.
    StateSpec`s: every state must declare ``row_additive`` (stamped from the
    class's ``_engine_row_additive`` opt-in at registration) and a ``sum``
    fold. Metrics without a registry (out-of-tree, hand-rolled ``_defaults``)
    resolve through the counted deprecated-attribute fallback.
    """
    reductions = getattr(metric, "_reductions", {})
    if not reductions:
        return False
    from torchmetrics_tpu.engine import statespec as _statespec

    return all(
        (sp := _statespec.spec_of(metric, attr, consumer="bucketing")).row_additive
        and sp.fold == "sum"
        for attr in reductions
    )


def batch_size(args: Sequence[Any]) -> Optional[int]:
    """The shared leading-axis size of the inputs, or None when there isn't one."""
    sizes = {a.shape[0] for a in args if getattr(a, "ndim", 0) >= 1}
    if len(sizes) != 1:
        return None
    return sizes.pop()


def pad_args(args: Sequence[Any], bucket: int) -> Tuple[Any, ...]:
    """Zero-pad every batched input's leading axis up to ``bucket`` rows.

    Zero rows are the universal pad: integer inputs land on class/label 0 and
    float inputs on 0.0 — both valid update inputs for the eligible metric
    families, and the pad-subtract identity removes whatever they contribute.
    """
    import jax.numpy as jnp

    out = []
    for a in args:
        if getattr(a, "ndim", 0) >= 1 and a.shape[0] < bucket:
            pad_widths = [(0, bucket - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
            out.append(jnp.pad(a, pad_widths))
        else:
            out.append(a)
    return tuple(out)


def pad_row_constants(args: Sequence[Any]) -> Tuple[Optional[np.ndarray], ...]:
    """One-row zero inputs matching ``args``' trailing shapes — the trace-time
    constants from which the compiled step derives the per-pad-row contribution.

    Non-batched (0-d) inputs yield ``None``: their live TRACED value must feed
    the unit computation — baking the first-seen value as a constant would make
    the subtraction wrong when that input changes under the same signature.
    """
    out = []
    for a in args:
        if getattr(a, "ndim", 0) >= 1:
            out.append(np.zeros((1,) + tuple(a.shape[1:]), dtype=np.dtype(str(a.dtype))))
        else:
            out.append(None)
    return tuple(out)
