"""Engine introspection counters.

Every :class:`CompiledUpdate`/:class:`FusedUpdate` owns an :class:`EngineStats`;
all live instances register in a module-level weak set so :func:`engine_report`
can aggregate a process-wide view without keeping dead metrics alive. The
counters are the driver-verifiable evidence surface: ``bench.py`` exports them
so "0 retraces after warmup" and "one dispatch per fused step" are recorded
numbers, not claims.
"""

from __future__ import annotations

import weakref
from collections import Counter
from typing import Any, Dict

_REGISTRY: "weakref.WeakSet[EngineStats]" = weakref.WeakSet()

_COUNTER_FIELDS = (
    "traces",  # signatures compiled (each = one XLA trace+compile)
    "cache_hits",  # steps served by an already-compiled executable
    "dispatches",  # compiled executions (fused: 1 per N-metric step)
    "metrics_updated",  # metric-updates performed via compiled steps (fused: N per step)
    "eager_fallbacks",  # steps that fell back to the eager Python path
    "donated_dispatches",  # dispatches that donated the state pytree
    "donation_copies",  # state leaves copied pre-dispatch to protect shared buffers
    "donation_fallbacks",  # dispatches that skipped donation (backend/policy)
    "bucketed_steps",  # steps that rode a shape bucket
    "bucket_pad_rows",  # total pad rows added across bucketed steps
    "bytes_moved",  # input + state bytes entering compiled dispatches
    # --- epoch engine (engine/epoch.py): packed sync + cached compute ---
    "packed_syncs",  # packed epoch syncs completed (vs eager per-tensor syncs)
    "sync_collectives",  # buffer collectives issued across all packed syncs
    "sync_metadata_gathers",  # metadata exchanges issued (0 for rank-invariant plans)
    "sync_bytes_moved",  # bytes through packed-sync collectives (gathered view)
    "sync_fold_traces",  # fold / fused sync→compute executables compiled
    "compute_traces",  # compute executables compiled (retraces = growth after warmup)
    "compute_dispatches",  # cached compute dispatches (incl. fused sync→compute)
    "compute_cache_hits",  # compute dispatches served without a re-trace
)


class EngineStats:
    """Mutable counter block for one engine instance."""

    __slots__ = ("owner", "fallback_reasons", "bucket_sizes", "__weakref__", *_COUNTER_FIELDS)

    def __init__(self, owner: str = "") -> None:
        self.owner = owner
        self.fallback_reasons: Counter = Counter()
        self.bucket_sizes: set = set()
        for f in _COUNTER_FIELDS:
            setattr(self, f, 0)
        _REGISTRY.add(self)

    def fallback(self, reason: str) -> None:
        self.eager_fallbacks += 1
        self.fallback_reasons[reason] += 1

    def reset(self) -> None:
        for f in _COUNTER_FIELDS:
            setattr(self, f, 0)
        self.fallback_reasons.clear()
        self.bucket_sizes.clear()

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {f: getattr(self, f) for f in _COUNTER_FIELDS}
        out["owner"] = self.owner
        out["bucket_count"] = len(self.bucket_sizes)
        if self.fallback_reasons:
            out["fallback_reasons"] = dict(self.fallback_reasons)
        return out

    def __repr__(self) -> str:
        body = ", ".join(f"{f}={getattr(self, f)}" for f in _COUNTER_FIELDS if getattr(self, f))
        return f"EngineStats({self.owner!r}, {body})"


def engine_report() -> Dict[str, Any]:
    """Aggregate counters over every live engine in the process."""
    total: Dict[str, Any] = {f: 0 for f in _COUNTER_FIELDS}
    reasons: Counter = Counter()
    buckets: set = set()
    engines = 0
    for st in list(_REGISTRY):
        engines += 1
        for f in _COUNTER_FIELDS:
            total[f] += getattr(st, f)
        reasons.update(st.fallback_reasons)
        buckets |= st.bucket_sizes
    total["engines"] = engines
    total["bucket_count"] = len(buckets)
    if reasons:
        total["fallback_reasons"] = dict(reasons)
    return total


def reset_engine_stats() -> None:
    """Zero every live engine's counters (bench scenario isolation)."""
    for st in list(_REGISTRY):
        st.reset()
