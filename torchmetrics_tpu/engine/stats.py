"""Engine introspection counters.

Every :class:`CompiledUpdate`/:class:`FusedUpdate` owns an :class:`EngineStats`;
all live instances register in a module-level weak set so :func:`engine_report`
can aggregate a process-wide view without keeping dead metrics alive. The
counters are the driver-verifiable evidence surface: ``bench.py`` exports them
so "0 retraces after warmup" and "one dispatch per fused step" are recorded
numbers, not claims.
"""

from __future__ import annotations

import weakref
from collections import Counter
from typing import Any, Dict

from torchmetrics_tpu.diag import trace as _diag

_REGISTRY: "weakref.WeakSet[EngineStats]" = weakref.WeakSet()

_COUNTER_FIELDS = (
    "traces",  # signatures compiled (each = one XLA trace+compile)
    "cache_hits",  # steps served by an already-compiled executable
    "dispatches",  # compiled executions (fused: 1 per N-metric step)
    "metrics_updated",  # metric-updates performed via compiled steps (fused: N per step)
    "eager_fallbacks",  # steps that fell back to the eager Python path
    "donated_dispatches",  # dispatches that donated the state pytree
    "donation_copies",  # state leaves copied pre-dispatch to protect shared buffers
    "donation_fallbacks",  # dispatches that skipped donation (backend/policy)
    "bucketed_steps",  # steps that rode a shape bucket
    "bucket_pad_rows",  # total pad rows added across bucketed steps
    "bytes_moved",  # input + state bytes entering compiled dispatches
    # --- multi-step scan dispatch (engine/scan.py): queued K-step drains ---
    "scan_dispatches",  # scan drains executed (each = ONE dispatch folding many steps)
    "scan_steps_folded",  # real update steps folded across all scan drains
    "scan_pad_steps",  # masked no-op padding steps added to fill K-buckets
    "scan_flushes",  # queue flushes (drains + discards), by reason in scan_flush_reasons
    # --- async pipelined dispatch (engine/async_dispatch.py): background drains ---
    "async_submits",  # buffers swapped out and handed to the background worker
    "async_dispatches",  # background drains the worker executed (overlapping the caller)
    "async_joins",  # observation joins that actually waited on in-flight work
    "async_join_wait_us",  # host µs observers spent waiting at joins (exported in seconds)
    "async_overlap_us",  # drain/sync µs overlapped with caller forward progress
    "async_backpressure_waits",  # submits that blocked on the bounded in-flight window
    "async_replayed_steps",  # steps replayed on the caller after a worker drain failed
    "async_prefetches",  # host arrays device_put-staged at enqueue, ahead of their drain
    # --- transactional layer (engine/txn.py): quarantine + fallback ladder ---
    "quarantined_batches",  # poisoned batches skipped in-graph (filled at the sanctioned read)
    "ladder_retries",  # dispatch failures that stepped down to a smaller bucket
    # --- numerics layer (engine/numerics.py): compensated accumulation + drift audit ---
    "compensated_steps",  # updates whose accumulate rode the in-graph two-sum
    "reanchors",  # epoch-boundary (value, residual) folds into a clean anchor
    "drift_probes",  # sampled drift-audit reads at the sanctioned boundary
    "drift_flags",  # probes whose relative drift exceeded TORCHMETRICS_TPU_DRIFT_RTOL
    # --- epoch engine (engine/epoch.py): packed sync + cached compute ---
    "packed_syncs",  # packed epoch syncs completed (vs eager per-tensor syncs)
    "sync_collectives",  # buffer collectives issued across all packed syncs
    "sync_metadata_gathers",  # metadata exchanges issued (0 for rank-invariant plans)
    "sync_bytes_moved",  # bytes through packed-sync collectives (gathered view)
    "sync_fold_traces",  # fold / fused sync→compute executables compiled
    "sync_divergence_flags",  # rank-divergent rank-invariant states flagged by the audit
    "sync_straggler_flags",  # packed syncs whose arrival skew exceeded the straggler threshold
    "sync_retries",  # bounded-collective retries spent inside packed exchanges
    "sync_degraded_folds",  # packed syncs folded over a degraded (survivor) membership
    "compute_traces",  # compute executables compiled (retraces = growth after warmup)
    "compute_dispatches",  # cached compute dispatches (incl. fused sync→compute)
    "compute_cache_hits",  # compute dispatches served without a re-trace
    # --- profiling layer (diag/profile.py): sampled completion probes ---
    "profile_probes",  # warm dispatches followed by a sanctioned block_until_ready probe
    # --- state-spec registry (engine/statespec.py): deprecation telemetry ---
    "spec_fallbacks",  # roles resolved via the deprecated string-prefix/attribute conventions
    # --- heavy-workload kernels (image/fid.py, detection/mean_ap.py): retained host paths ---
    "fid_host_eighs",  # FID Fréchet computes routed to host LAPACK via TORCHMETRICS_TPU_FID_HOST_EIGH
    "map_host_evals",  # mAP computes evaluated by the retained host matcher (list/RLE route)
    # --- SPMD sharded-state engine (parallel/sharding.py): mesh placement ---
    "shard_states",  # states placed distributed via a resolved shard rule (born or re-placed)
    "psum_syncs",  # additive sharded states whose sync lowered to in-graph psum (gather skipped)
    "gather_skipped",  # sharded states the packed host gather skipped entirely
    # --- 2-D data×state mesh (parallel/sharding.py + engine/epoch.py) ---
    "shard_degrades",  # shard-rule resolutions degraded to replication (no mesh / indivisible dim)
    "ingraph_syncs",  # packed exchanges that rode the data axis in-graph (zero host collectives)
    "sync_noop_plans",  # packed syncs skipped wholesale: every state live-sharded, nothing to pack
    # --- persistent executable cache (engine/persist.py): zero-cold-start serving ---
    "persist_hits",  # compiles served by deserializing a persisted executable (no lower/compile)
    "persist_misses",  # compiles that found no loadable artifact (absent/stale/corrupt — counted, never wrong)
    "prewarm_replays",  # manifest rows replayed by prewarm() before traffic landed
    # --- federated aggregation plane (serve/federation.py): cross-pod folds ---
    "federation_ingests",  # pod snapshots accepted (version+CRC verified, watermark advanced)
    "federation_folds",  # global folds executed over the verified pod membership
    "federation_degraded_folds",  # global folds over a degraded (pod-excluding) membership
    "federation_stale_skips",  # snapshots rejected by the watermark/staleness dedupe
    # --- fleet observability plane (serve/fleet.py): cross-pod telemetry federation ---
    "fleet_pulls",  # pod telemetry envelopes accepted (version+CRC verified, watermark advanced)
    "fleet_merges",  # fleet-wide telemetry merges over the fresh pod membership
    "fleet_degraded_pulls",  # pods excluded from a pull/merge round (fault, stale, never pulled)
    # --- declarative SLO engine (diag/slo.py): rolling-window objective evaluation ---
    "slo_evaluations",  # SLO evaluation passes (every spec, fast+slow burn windows)
    "slo_breaches",  # SLO compliance transitions into breach (slo.breach events)
    "slo_recoveries",  # SLO compliance transitions back to healthy (slo.recover events)
    # --- value provenance & freshness plane (diag/lineage.py) ---
    "lineage_records",  # ValueProvenance records built at observation sites
    "lineage_spans",  # causal spans opened at enqueue (one per drain generation)
    "lineage_coverage_folds",  # coverage attestations stamped at fold/merge sites
)


class EngineStats:
    """Mutable counter block for one engine instance."""

    __slots__ = (
        "owner", "fallback_reasons", "bucket_sizes", "retrace_causes",
        "scan_flush_reasons", "__weakref__", *_COUNTER_FIELDS,
    )

    def __init__(self, owner: str = "") -> None:
        self.owner = owner
        self.fallback_reasons: Counter = Counter()
        self.bucket_sizes: set = set()
        self.retrace_causes: Counter = Counter()  # attributed causes of post-initial compiles
        self.scan_flush_reasons: Counter = Counter()  # scan-queue flushes by reason
        for f in _COUNTER_FIELDS:
            setattr(self, f, 0)
        _REGISTRY.add(self)

    def fallback(self, reason: str) -> None:
        self.eager_fallbacks += 1
        self.fallback_reasons[reason] += 1
        # every eager fallback is also a flight-recorder fact (diag/trace.py);
        # the single hook here keeps every engine's fallback sites covered
        _diag.record("fallback", self.owner, reason=reason)

    def reset(self) -> None:
        for f in _COUNTER_FIELDS:
            setattr(self, f, 0)
        self.fallback_reasons.clear()
        self.bucket_sizes.clear()
        self.retrace_causes.clear()
        self.scan_flush_reasons.clear()

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {f: getattr(self, f) for f in _COUNTER_FIELDS}
        out["owner"] = self.owner
        out["bucket_count"] = len(self.bucket_sizes)
        # sorted: JSON exports of the same state must be byte-identical
        if self.fallback_reasons:
            out["fallback_reasons"] = {k: self.fallback_reasons[k] for k in sorted(self.fallback_reasons)}
        if self.retrace_causes:
            out["retrace_causes"] = {k: self.retrace_causes[k] for k in sorted(self.retrace_causes)}
        if self.scan_flush_reasons:
            out["scan_flush_reasons"] = {k: self.scan_flush_reasons[k] for k in sorted(self.scan_flush_reasons)}
        return out

    def __repr__(self) -> str:
        body = ", ".join(f"{f}={getattr(self, f)}" for f in _COUNTER_FIELDS if getattr(self, f))
        return f"EngineStats({self.owner!r}, {body})"


def engine_report(include_events: bool = False, reset: bool = False) -> Dict[str, Any]:
    """Aggregate counters over every live engine in the process.

    Args:
        include_events: attach the active flight recorder's per-kind event
            counts (and drop count) under ``"diag"`` — empty when recording is
            off (see :func:`torchmetrics_tpu.diag.diag_context`).
        reset: zero every engine's counters AND clear the diag ring buffer
            after reading, so bench scenarios and tests start the next
            measurement from a clean recorder.
    """
    total: Dict[str, Any] = {f: 0 for f in _COUNTER_FIELDS}
    reasons: Counter = Counter()
    causes: Counter = Counter()
    flushes: Counter = Counter()
    buckets: set = set()
    engines = 0
    for st in list(_REGISTRY):
        engines += 1
        for f in _COUNTER_FIELDS:
            total[f] += getattr(st, f)
        reasons.update(st.fallback_reasons)
        causes.update(st.retrace_causes)
        flushes.update(st.scan_flush_reasons)
        buckets |= st.bucket_sizes
    total["engines"] = engines
    total["bucket_count"] = len(buckets)
    # deterministically sorted: byte-stable JSON exports (see diag/telemetry.py)
    if reasons:
        total["fallback_reasons"] = {k: reasons[k] for k in sorted(reasons)}
    if causes:
        total["retrace_causes"] = {k: causes[k] for k in sorted(causes)}
    if flushes:
        total["scan_flush_reasons"] = {k: flushes[k] for k in sorted(flushes)}
    if include_events:
        rec = _diag.active_recorder()
        total["diag"] = (
            {"events": {k: rec.counts[k] for k in sorted(rec.counts)}, "dropped": rec.dropped}
            if rec is not None
            else {"events": {}, "dropped": 0}
        )
    if reset:
        reset_engine_stats()
    return total


def reset_engine_counters() -> None:
    """Zero every live engine's counters, leaving any recorder untouched.

    For callers that manage their own :class:`~torchmetrics_tpu.diag.trace.
    FlightRecorder` lifetime (``diag_report(rec, reset=True)`` clears the
    recorder it actually reported on, not whichever happens to be active).
    """
    for st in list(_REGISTRY):
        st.reset()


def reset_engine_stats() -> None:
    """Zero every live engine's counters, the diag ring buffer, the cost
    ledger, the sentinel registry, the quarantine registry, the latency
    histograms, AND the profiler's probe accounting.

    The shared reset keeps every evidence surface (counters, flight recorder,
    per-executable costs, health sentinels, latency distributions, probe
    counts) in lockstep: a bench scenario that resets one but not the others
    would attribute the previous scenario's events/costs/flags/tails to the
    fresh run.
    """
    from torchmetrics_tpu.diag.costs import reset_ledger
    from torchmetrics_tpu.diag.hist import reset_histograms
    from torchmetrics_tpu.diag.lineage import reset_lineage
    from torchmetrics_tpu.diag.profile import reset_profile
    from torchmetrics_tpu.diag.sentinel import reset_sentinels
    from torchmetrics_tpu.diag.slo import reset_slo
    from torchmetrics_tpu.engine.persist import reset_persist_stats
    from torchmetrics_tpu.engine.txn import reset_quarantine
    from torchmetrics_tpu.parallel.resilience import reset_resilience
    from torchmetrics_tpu.serve.stats import reset_serve_stats

    reset_engine_counters()
    _diag.clear_recorder()
    reset_ledger()
    reset_sentinels()
    reset_quarantine()
    reset_histograms()
    reset_profile()
    reset_resilience()
    reset_serve_stats()
    reset_persist_stats()
    reset_slo()
    reset_lineage()
