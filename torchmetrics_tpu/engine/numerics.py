"""Long-horizon numerical resilience — compensated in-graph accumulation,
overflow-safe count widening, and the sampled precision-drift audit.

The fused engine compiles whole epochs into donated float32 graphs, so
accumulation error is silent and unbounded: after ~10⁷ updates a float32 sum
absorbs increments entirely (``|inc| < ulp(acc)`` makes the update a no-op),
int32 counts overflow, and mean folds drift. This module defends the
*correctness of a healthy accumulator over time* — the robustness gap between
an epoch-scale engine and an unbounded serving stream — with four pieces:

- **Compensated accumulation** (``TORCHMETRICS_TPU_COMPENSATED=1`` /
  :func:`compensated_context`): eligible float states with
  ``dist_reduce_fx="sum"|"mean"`` accumulate through a Kahan–Babuška–Neumaier
  two-sum compiled INTO the donated update graph. The update body runs on a
  ZEROED copy of each compensated state, so it returns the pure batch
  contribution; the recomposition ``value, err = two_sum(value, contribution
  + residual)`` then folds the running residual back into every increment
  (the feedback form — the residual stays sub-ulp of the accumulator, so
  error growth is O(ε) instead of O(N·ε); Knuth's branch-free two-sum keeps
  the error term exact regardless of magnitudes). The residual rides the
  state pytree under the reserved :data:`STATE_KEY` — pad-subtract-exempt
  like ``__sentinel__``/``__quarantine__`` — and lives on the metric as the
  :data:`ATTR` dict between steps. Zero host transfers, zero warm retraces:
  the whole transform is a handful of fused adds in the same executable.
- **Absorption detection**: when an update's entire nonzero contribution fails
  to move the accumulator (``fl(acc + inc) == acc``), the new sticky
  ``precision_loss`` sentinel bit (``diag/sentinel.py``, 0x40) is raised
  in-graph and ORed cross-rank by the existing sentinel spec. Under
  compensation the increment is *preserved* in the residual rather than lost
  — the bit says "a naive accumulator would be silently wrong from here on".
- **Sampled drift audit**: with profiling active (the PR-5 ``every_n`` probe
  machinery), every Nth *warm* dispatch reads the (value, residual) pair at
  the sanctioned ``drift-probe`` boundary and folds it into a float64
  reference on the host — the relative drift of the naive float32 value from
  that reference lands in the ``diag/hist.py`` registry (``drift_ppb``
  series, parts-per-billion so the log buckets resolve 1e-9..1e-2) and a
  drift past ``TORCHMETRICS_TPU_DRIFT_RTOL`` records a ``numerics.drift``
  event + ``EngineStats.drift_flags``. Unsampled steps are byte-identical to
  an unaudited run (the probe only reads).
- **Periodic re-anchoring**: :func:`reanchor` folds (value, residual) into a
  clean anchor — called at every ``compute()`` epoch boundary, inside the
  packed-sync two-sum fold (``parallel/packing.py``), and on-the-fly by
  ``state_dict`` so snapshots persist the anchored total (restore then
  starts with a zero residual; see ``parallel/elastic.py``).

Overflow-safe widening: :func:`count_dtype` resolves the dtype device-side
counters accumulate in — int64 when the x64 flag is up (the promotion happens
at creation, so retrace attribution never sees a mid-stream dtype flip; under
x64 *warmup* the attribution reads dtype-change exactly once, as PR 3 pinned),
int32 otherwise (where the ``overflow_suspect`` sentinel bit is the guard).
Host-side counts (``Metric._update_count``) are Python ints — arbitrary
precision — and :func:`py_count` coerces numpy scalars back to that before any
additive fold so a ``np.int32`` count can never wrap silently.

Enable the same compensation mode on EVERY rank of a world: the residual
joins the packed sync's reduce buffers (a paired spec per compensated state,
folded by two-sum — not naive add), so asymmetric enablement desynchronizes
the buffer layout — the same rule the sentinel, audit, and quarantine knobs
already document, enforced by the plan-signature/layout checks.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Callable, Dict, Generator, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import profile as _profile
from torchmetrics_tpu.diag import trace as _diag

__all__ = [
    "ATTR",
    "COMPENSATED_ENV_VAR",
    "DRIFT_RTOL_ENV_VAR",
    "STATE_KEY",
    "SYNC_RES_PREFIX",
    "anchored_value",
    "build_compensation",
    "comp_state_names",
    "compensated_context",
    "compensated_enabled",
    "compensation_active",
    "count_dtype",
    "drift_rtol",
    "eager_update",
    "ensure_residuals",
    "maybe_drift_probe",
    "py_count",
    "reanchor",
    "set_compensated",
    "set_drift_rtol",
    "set_residual",
    "two_sum",
]

COMPENSATED_ENV_VAR = "TORCHMETRICS_TPU_COMPENSATED"
DRIFT_RTOL_ENV_VAR = "TORCHMETRICS_TPU_DRIFT_RTOL"

#: reserved pytree key for the residual dict inside compiled step states —
#: aliased from the canonical declaration (engine/statespec.py RIDER_KEYS);
#: tmlint rule TM301 forbids respelling the literal outside that module
from torchmetrics_tpu.engine.statespec import COMPENSATION_KEY as STATE_KEY  # noqa: E402
#: the attribute carrying the live residual dict ({state attr: residual array})
ATTR = "_comp_residuals"
#: packed-sync fold output keys carrying a state's post-fold residual
SYNC_RES_PREFIX = "__comp_res__::"

#: default relative-drift threshold for the sampled audit. Under healthy
#: compensation the feedback form keeps the residual sub-ulp of the
#: accumulator, so measured drift stays below ~2**-24 (≈6e-8): the default
#: only fires on pathological states (merge-accumulated shard residue, a
#: corrupt restore, operator-injected state) — tighten the knob to audit at
#: the healthy sub-ulp scale
DEFAULT_DRIFT_RTOL = 1e-5

_enabled_override: Optional[bool] = None
_rtol_override: Optional[float] = None


# ------------------------------------------------------------------ policy


def compensated_enabled() -> bool:
    """Whether eligible updates accumulate through the compensated two-sum.

    Unrecognized env values fail loud (the PR-7 ``TORCHMETRICS_TPU_QUARANTINE``
    contract): a typo must not silently disable the protection it was set to
    enable.
    """
    if _enabled_override is not None:
        return _enabled_override
    raw = os.environ.get(COMPENSATED_ENV_VAR, "").strip().lower()
    if raw in ("", "0", "off"):
        return False
    if raw in ("1", "on"):
        return True
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    raise TorchMetricsUserError(
        f"{COMPENSATED_ENV_VAR} must be '0'/'off' or '1'/'on' (got {raw!r})"
    )


def set_compensated(value: Optional[bool]) -> None:
    """Force compensation on/off process-wide; ``None`` restores env/default."""
    global _enabled_override
    _enabled_override = value


@contextmanager
def compensated_context(enabled: bool = True) -> Generator[None, None, None]:
    """Scoped compensation enablement (tests, benches). Toggling mid-stream
    retraces the affected signatures once (the residual rider is a
    ``treedef-change``); enable on EVERY rank of a world or none."""
    global _enabled_override
    prev = _enabled_override
    _enabled_override = enabled
    try:
        yield
    finally:
        _enabled_override = prev


def drift_rtol() -> float:
    """The relative-drift threshold past which the sampled audit flags.

    An unparseable env value fails loud instead of silently reverting to the
    default — the same contract as ``TORCHMETRICS_TPU_SNAPSHOT_EVERY``.
    """
    if _rtol_override is not None:
        return _rtol_override
    raw = os.environ.get(DRIFT_RTOL_ENV_VAR, "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

            raise TorchMetricsUserError(
                f"{DRIFT_RTOL_ENV_VAR} must be a float (got {raw!r})"
            ) from None
    return DEFAULT_DRIFT_RTOL


def set_drift_rtol(value: Optional[float]) -> None:
    """Override the drift threshold; ``None`` restores env/default."""
    global _rtol_override
    _rtol_override = None if value is None else float(value)


# ------------------------------------------------------------------ widening


def count_dtype() -> Any:
    """The dtype device-side counters accumulate in: int64 under x64, else int32.

    Resolved at counter CREATION, so a process never flips a live counter's
    dtype mid-stream (which would read as an unattributed retrace); under the
    x64 flag the engine's retrace attribution sees the promotion exactly once,
    at the first post-enable compile (``dtype-change``, the PR-3 contract).
    Without x64 int64 does not exist on device — int32 stays, guarded by the
    ``overflow_suspect`` sentinel bit at half-range.
    """
    import jax
    import jax.numpy as jnp

    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def py_count(value: Any) -> int:
    """Coerce a count to a Python int (arbitrary precision) before folding.

    ``Metric._update_count`` is host-side state; wrappers and checkpoints
    occasionally hand it back as a numpy scalar, and ``np.int32 + np.int32``
    WRAPS silently near 2**31 — the exact overflow ``merge_state``'s additive
    fold must survive. One ``int()`` at every fold boundary removes the class.
    """
    return int(value)


# ------------------------------------------------------------------ two-sum


def two_sum(a: Any, b: Any) -> Tuple[Any, Any]:
    """Knuth's branch-free two-sum: ``s = fl(a + b)`` and the EXACT error term.

    Unlike the fast (Dekker) variant this needs no magnitude branch, so it
    lowers to six fused adds inside the update graph — valid for any (a, b).
    """
    s = a + b
    bp = s - a
    ap = s - bp
    return s, (a - ap) + (b - bp)


def anchored_value(value: Any, residual: Any) -> Any:
    """The re-anchored accumulator: ``fl(value + residual)`` (read-only fold)."""
    return two_sum(value, residual)[0]


# ------------------------------------------------------------------ eligibility


def comp_state_names(metric: Any) -> Tuple[str, ...]:
    """The states of ``metric`` the compensated two-sum applies to.

    Eligibility is a pure function of the metric DEFINITION (registered
    :class:`~torchmetrics_tpu.engine.statespec.StateSpec` roles, registered
    defaults) — never of live values — so every rank of a world resolves the
    same set and the packed buffer layout stays symmetric:

    - the state's spec declares additivity (``state_additive`` on the scalar
      aggregators, or the bucketing family's ``row_additive``, both stamped
      from the class declaration at ``add_state`` time) — the zero-state trick
      that recovers the pure batch contribution is only exact for
      ``new = old + g(batch)`` updates;
    - the spec's fold is ``sum`` or ``mean``;
    - the registered default is a float array (integer counts widen via
      :func:`count_dtype` instead; there is no residual to track exactly).
    """
    import jax.numpy as jnp

    from torchmetrics_tpu.engine import statespec as _statespec

    names = []
    for attr in getattr(metric, "_reductions", {}):
        spec = _statespec.spec_of(metric, attr, consumer="compensation")
        if spec.fold not in ("sum", "mean"):
            continue
        if not (spec.state_additive or spec.row_additive):
            continue
        default = metric._defaults[attr]
        if isinstance(default, list):
            continue
        if jnp.issubdtype(default.dtype, jnp.floating):
            names.append(attr)
    return tuple(names)


def compensation_active(metric: Any) -> bool:
    """Whether this metric's updates ride the compensated path right now."""
    return compensated_enabled() and bool(comp_state_names(metric))


def ensure_residuals(metric: Any) -> Dict[str, Any]:
    """The metric's residual dict, created (zeros) on first use."""
    res = metric.__dict__.get(ATTR)
    if res is None:
        import jax.numpy as jnp

        res = {k: jnp.zeros_like(getattr(metric, k)) for k in comp_state_names(metric)}
        setattr(metric, ATTR, res)
    return res


def set_residual(metric: Any, attr: str, value: Any) -> None:
    """Install one state's residual (packed-sync fold output path)."""
    res = dict(metric.__dict__.get(ATTR) or {})
    res[attr] = value
    setattr(metric, ATTR, res)


# ------------------------------------------------------------------ the in-graph transform


def build_compensation(
    metric: Any,
    names: Sequence[str],
    admission: Optional[Callable[[Sequence[Any]], Any]] = None,
) -> Callable[[Dict[str, Any], Dict[str, Any], Sequence[Any]], Dict[str, Any]]:
    """The jittable ``(old_state, result, flat) -> result`` recomposition.

    ``result``'s compensated entries hold the pure batch CONTRIBUTION (the
    update body ran on zeroed copies of those states; pad-subtract has already
    removed pad rows from the contribution, never from the preserved old
    value). The transform folds ``contribution + residual`` into the old value
    via :func:`two_sum` and carries the exact error as the new residual.

    Sentinel rider interplay: the run body SKIPPED its health fold (it only
    saw zeroed copies of the compensated states), so — without quarantine —
    the NaN/Inf/overflow checks fold here over the RECOMPOSED final states;
    with the quarantine ``admission`` present the transaction folds them over
    the SELECTED states instead (the PR-7 contract). The sticky
    ``precision_loss`` bit is raised when any nonzero contribution failed to
    move its accumulator, masked by ``admission`` so a poisoned batch's
    absorbed garbage cannot stick a health bit the transaction is about to
    roll back.
    """
    from torchmetrics_tpu.diag import sentinel as _sentinel
    from torchmetrics_tpu.engine import txn as _txn

    names = tuple(names)

    def comp(old: Dict[str, Any], result: Dict[str, Any], flat: Sequence[Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        residual = old[STATE_KEY]
        out = dict(result)
        new_res = dict(residual)
        absorbed = jnp.asarray(False)
        for k in names:
            a = old[k]
            b = result[k]  # pure batch contribution
            s, err = two_sum(a, b + residual[k])
            out[k] = s
            new_res[k] = err
            absorbed = absorbed | ((b != 0) & (s == a)).any()
        out[STATE_KEY] = new_res
        if _sentinel.STATE_KEY in out:
            flags = out[_sentinel.STATE_KEY]
            if admission is not None:
                absorbed = absorbed & ~admission(flat)
            else:
                final = {
                    k: v
                    for k, v in out.items()
                    if k not in (STATE_KEY, _sentinel.STATE_KEY, _txn.STATE_KEY)
                }
                flags = _sentinel.update_flags(flags, final, metric)
            out[_sentinel.STATE_KEY] = flags | jnp.where(
                absorbed, jnp.int32(_sentinel.FLAG_PRECISION_LOSS), jnp.int32(0)
            )
        return out

    return comp


# ------------------------------------------------------------------ eager parity


def eager_update(metric: Any, run_update: Callable[[], None]) -> None:
    """Compensated eager update — the engine-off parity path.

    Same zero-state trick as the compiled transform: the compensated states
    enter the raw update body zeroed, the body leaves the pure contribution
    behind, and the two-sum recomposition (residual fed back into the
    increment) runs as a handful of eager jnp ops — no host transfer, no
    double execution of the body.
    """
    import jax.numpy as jnp

    from torchmetrics_tpu.diag import sentinel as _sentinel

    names = comp_state_names(metric)
    residual = ensure_residuals(metric)
    old = {k: getattr(metric, k) for k in names}
    for k in names:
        setattr(metric, k, jnp.zeros_like(old[k]))
    try:
        run_update()
    except BaseException:
        for k, v in old.items():  # a failed/raising update must not leave zeroed state
            setattr(metric, k, v)
        raise
    new_res = dict(residual)
    absorbed = jnp.asarray(False)
    for k in names:
        b = getattr(metric, k)  # pure batch contribution
        s, err = two_sum(old[k], b + residual[k])
        setattr(metric, k, s)
        new_res[k] = err
        absorbed = absorbed | ((b != 0) & (s == old[k])).any()
    setattr(metric, ATTR, new_res)
    if _sentinel.sentinel_enabled():
        flags = _sentinel.ensure_flags(metric)
        setattr(
            metric,
            _sentinel.ATTR,
            flags
            | jnp.where(absorbed, jnp.int32(_sentinel.FLAG_PRECISION_LOSS), jnp.int32(0)),
        )
    _stats_for(metric).compensated_steps += 1


def _stats_for(metric: Any):
    from torchmetrics_tpu.engine import txn as _txn

    return _txn._stats_for(metric)


# ------------------------------------------------------------------ re-anchoring


def reanchor(metric: Any) -> bool:
    """Fold (value, residual) into a clean anchor — the epoch-boundary fold.

    Pure device ops (no host read): each compensated value absorbs its
    residual through one two-sum, and the residual keeps only the sub-ulp
    remainder, so error growth over an unbounded stream restarts from a clean
    anchor at every epoch. Returns True when something was folded.
    """
    res = metric.__dict__.get(ATTR)
    if not res:
        return False
    new_res = {}
    for k, r in res.items():
        v = getattr(metric, k, None)
        if v is None or getattr(v, "shape", None) != getattr(r, "shape", None):
            new_res[k] = r  # state moved under the residual (e.g. mid-restore)
            continue
        s, rem = two_sum(v, r)
        setattr(metric, k, s)
        new_res[k] = rem
    setattr(metric, ATTR, new_res)
    _stats_for(metric).reanchors += 1
    _diag.record("numerics.reanchor", type(metric).__name__, states=len(new_res))
    return True


# ------------------------------------------------------------------ drift audit


def maybe_drift_probe(metric: Any, stats: Any, owner: Optional[str] = None) -> Optional[float]:
    """Sampled precision-drift audit — every Nth warm dispatch, sanctioned.

    Reuses the PR-5 probe machinery (:func:`~torchmetrics_tpu.diag.profile.
    probe_due` under an active profile scope) and its boundary rules: the
    (value, residual) pair is read ONLY inside ``transfer_allowed("drift-
    probe")``, folded into a float64 reference on the host, and the worst
    relative drift of the naive value from that reference is recorded into the
    ``drift_ppb`` histogram series (parts-per-billion keeps 1e-9..1e-2 drifts
    inside the log-bucket range). Drift past :func:`drift_rtol` is a counted
    ``numerics.drift`` event. Unsampled steps are untouched — byte-for-byte.
    """
    res = metric.__dict__.get(ATTR)
    if not res:
        return None
    # ``owner`` distinguishes fused members sharing one stats block: each
    # compensated member needs its OWN probe cadence, or the shared counter
    # advances M times per step and the sample lands on the same member forever
    owner = owner or stats.owner
    if not _profile.probe_due(owner, "drift"):
        return None
    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    worst = 0.0
    with transfer_allowed("drift-probe"):
        for k, r in res.items():
            value = np.asarray(getattr(metric, k), dtype=np.float64)
            reference = value + np.asarray(r, dtype=np.float64)
            denom = np.maximum(np.abs(reference), np.finfo(np.float64).tiny)
            rel = float(np.max(np.abs(reference - value) / denom)) if value.size else 0.0
            if np.isnan(rel):
                # a NaN in (value, residual) is the corrupt-restore pathology
                # this audit exists to catch — infinite drift, never "0.0"
                # (max(0.0, nan) would silently keep the healthy reading)
                rel = float("inf")
            worst = max(worst, rel)
    stats.drift_probes += 1
    _hist.observe(owner, "update", "drift_ppb", worst * 1e9)
    rtol = drift_rtol()
    if worst > rtol:
        stats.drift_flags += 1
        _diag.record(
            "numerics.drift", owner, rel=round(worst, 12), rtol=rtol,
        )
    return worst
