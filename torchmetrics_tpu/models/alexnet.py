"""Flax AlexNet feature slices for LPIPS.

Mirrors the vendored ``Alexnet`` in the reference (``functional/image/lpips.py:91-133``):
five taps at the post-relu activations of torchvision ``alexnet().features`` layers
1/4/7/9/11 (channel dims 64/192/384/256/256), which feed the bundled ``alex`` LPIPS
linear heads. ``from_torch_state_dict`` converts a torchvision checkpoint
(layer-indexed keys ``features.N.weight``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

Array = jax.Array

# torchvision alexnet.features conv layers: index -> (width, kernel, stride, pad)
_CONVS = {0: (64, 11, 4, 2), 3: (192, 5, 1, 2), 6: (384, 3, 1, 1), 8: (256, 3, 1, 1), 10: (256, 3, 1, 1)}
# reference slice boundaries (lpips.py:104-114): maxpool(3,2) before convs 3 and 6
_TAPS = (0, 3, 6, 8, 10)
_POOL_BEFORE = (3, 6)


if nn is not None:

    class AlexNetFeatures(nn.Module):
        """``__call__`` maps NCHW/NHWC images -> 5 post-relu slice features (NHWC)."""

        @nn.compact
        def __call__(self, x: Array) -> List[Array]:
            if x.shape[1] == 3 and x.shape[-1] != 3:  # NCHW -> NHWC
                x = jnp.transpose(x, (0, 2, 3, 1))
            outs = []
            for li in _TAPS:
                if li in _POOL_BEFORE:
                    x = nn.max_pool(x, (3, 3), strides=(2, 2))
                width, k, s, p = _CONVS[li]
                x = nn.Conv(
                    width, (k, k), strides=(s, s), padding=((p, p), (p, p)), name=f"conv{li}"
                )(x)
                x = nn.relu(x)
                outs.append(x)
            return outs

else:  # pragma: no cover
    AlexNetFeatures = None  # type: ignore[assignment,misc]


def from_torch_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Convert torchvision ``alexnet`` (or bare ``features``) weights to flax variables."""
    import numpy as np

    prefix = "features." if any(k.startswith("features.") for k in state_dict) else ""
    params: Dict[str, Any] = {}
    for li in _TAPS:
        w = np.asarray(state_dict[f"{prefix}{li}.weight"])  # (O, I, kH, kW)
        b = np.asarray(state_dict[f"{prefix}{li}.bias"])
        params[f"conv{li}"] = {"kernel": jnp.asarray(w.transpose(2, 3, 1, 0)), "bias": jnp.asarray(b)}
    return {"params": params}


def alexnet_lpips_extractor(
    state_dict: Optional[Mapping[str, Any]] = None,
    variables: Optional[Dict[str, Any]] = None,
):
    """Build the ``feats_fn`` the LPIPS pipeline injects: NCHW in -> 5 NCHW slice maps.

    Deterministic random init without weights (see ``vgg.py`` — nothing is bundled for
    backbones; the learned LPIPS heads ARE bundled, so the pipeline runs end-to-end).
    """
    if nn is None:  # pragma: no cover
        raise ModuleNotFoundError("flax is required for the built-in AlexNet extractor")
    model = AlexNetFeatures()
    if variables is None:
        if state_dict is not None:
            variables = from_torch_state_dict(state_dict)
        else:
            variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 64, 64), jnp.float32))

    def feats_fn(imgs: Array) -> List[Array]:
        outs = model.apply(variables, imgs)
        return [jnp.transpose(o, (0, 3, 1, 2)) for o in outs]

    return jax.jit(feats_fn)
