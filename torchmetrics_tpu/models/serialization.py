"""Flax-variable <-> npz serialization for converted backbone weights.

The model-backed metrics (FID/KID/IS, LPIPS, and the HF-backed text/multimodal
stack) accept converted weights; this module defines the on-disk format the
``scripts/convert_backbones.py`` recipe produces: one ``.npz`` whose keys are
``/``-joined paths into the flax variables pytree (``params/Conv_0/kernel``),
loadable without torch.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np


def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for key, val in tree.items():
        path = f"{prefix}/{key}" if prefix else str(key)
        if isinstance(val, dict):
            out.update(_flatten(val, path))
        else:
            out[path] = np.asarray(val)
    return out


def save_variables_npz(path: str, variables: Dict[str, Any]) -> int:
    """Write a flax variables pytree to ``path``; returns total parameter count."""
    flat = _flatten(variables)
    np.savez(path, **flat)
    return int(sum(v.size for v in flat.values()))


def load_variables_npz(path: str) -> Dict[str, Any]:
    """Load a converted-backbone npz back into the nested flax variables pytree."""
    tree: Dict[str, Any] = {}
    with np.load(path) as data:
        for key in data.files:
            node = tree
            parts = key.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = jnp.asarray(data[key])
    return tree


def count_params(variables: Dict[str, Any]) -> int:
    """Total leaf-array element count — the cheap integrity check for a conversion."""
    return int(sum(v.size for v in _flatten(variables).values()))
