"""Frozen feature-extractor architectures backing the model-based metrics.

The reference embeds pretrained torch networks inside its metrics — torch-fidelity's
InceptionV3 for FID/KID/IS (``src/torchmetrics/image/fid.py:52-157``), vendored
SqueezeNet/AlexNet/VGG16 for LPIPS (``functional/image/lpips.py:59-187``), HF
CLIP/BERT for CLIPScore/BERTScore. Here the architectures are native Flax modules that
run on the TPU inside jitted metric updates; pretrained weights are loaded by
converting a torch/torchvision state dict (no weights are bundled — this environment
has zero egress).
"""

from torchmetrics_tpu.models.alexnet import AlexNetFeatures, alexnet_lpips_extractor
from torchmetrics_tpu.models.inception import InceptionV3, inception_v3_extractor
from torchmetrics_tpu.models.squeezenet import SqueezeNetFeatures, squeezenet_lpips_extractor
from torchmetrics_tpu.models.vgg import VGG16Features, vgg16_lpips_extractor

__all__ = [
    "AlexNetFeatures",
    "InceptionV3",
    "SqueezeNetFeatures",
    "VGG16Features",
    "alexnet_lpips_extractor",
    "inception_v3_extractor",
    "squeezenet_lpips_extractor",
    "vgg16_lpips_extractor",
]
