"""Flax SqueezeNet-1.1 feature slices for LPIPS.

Mirrors the vendored ``SqueezeNet`` in the reference (``functional/image/lpips.py:59-88``):
seven taps over torchvision ``squeezenet1_1().features`` at slice boundaries
[0:2), [2:5), [5:8), [8:10), [10:11), [11:12), [12:13) — channel dims
64/128/256/384/384/512/512, feeding the bundled ``squeeze`` LPIPS heads.

torchvision's max pools use ``ceil_mode=True``; emulated here by right/bottom padding
with ``-inf`` when the spatial extent doesn't divide evenly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

Array = jax.Array

# torchvision squeezenet1_1.features: Fire(squeeze, expand1x1, expand3x3) per index
_FIRES = {3: (16, 64, 64), 4: (16, 64, 64), 6: (32, 128, 128), 7: (32, 128, 128),
          9: (48, 192, 192), 10: (48, 192, 192), 11: (64, 256, 256), 12: (64, 256, 256)}
_POOL_BEFORE = (3, 6, 9)  # MaxPool2d(3, 2, ceil_mode=True) at features indices 2/5/8
_SLICE_ENDS = (1, 4, 7, 9, 10, 11, 12)  # last features-index of each of the 7 taps


def _ceil_max_pool(x: Array) -> Array:
    """3x3/stride-2 max pool with torch ``ceil_mode=True`` semantics (NHWC)."""
    h, w = x.shape[1], x.shape[2]
    pad_h = (-(h - 3)) % 2
    pad_w = (-(w - 3)) % 2
    if pad_h or pad_w:
        x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)), constant_values=-jnp.inf)
    return nn.max_pool(x, (3, 3), strides=(2, 2))


if nn is not None:

    class Fire(nn.Module):
        """squeeze 1x1 -> relu -> [expand 1x1 | expand 3x3] -> relu -> concat."""

        squeeze: int
        expand1: int
        expand3: int

        @nn.compact
        def __call__(self, x: Array) -> Array:
            x = nn.relu(nn.Conv(self.squeeze, (1, 1), name="squeeze")(x))
            e1 = nn.relu(nn.Conv(self.expand1, (1, 1), name="expand1x1")(x))
            e3 = nn.relu(nn.Conv(self.expand3, (3, 3), padding=((1, 1), (1, 1)), name="expand3x3")(x))
            return jnp.concatenate([e1, e3], axis=-1)

    class SqueezeNetFeatures(nn.Module):
        """``__call__`` maps NCHW/NHWC images -> 7 slice features (NHWC)."""

        @nn.compact
        def __call__(self, x: Array) -> List[Array]:
            if x.shape[1] == 3 and x.shape[-1] != 3:  # NCHW -> NHWC
                x = jnp.transpose(x, (0, 2, 3, 1))
            x = nn.Conv(64, (3, 3), strides=(2, 2), padding="VALID", name="conv0")(x)
            x = nn.relu(x)
            outs = [x]  # tap 1: features[0:2)
            for li in range(3, 13):
                if li in _POOL_BEFORE:
                    x = _ceil_max_pool(x)
                if li in _FIRES:
                    s, e1, e3 = _FIRES[li]
                    x = Fire(s, e1, e3, name=f"fire{li}")(x)
                if li in _SLICE_ENDS:
                    outs.append(x)
            return outs

else:  # pragma: no cover
    SqueezeNetFeatures = None  # type: ignore[assignment,misc]


def from_torch_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Convert torchvision ``squeezenet1_1`` (or bare ``features``) weights to flax variables."""
    import numpy as np

    prefix = "features." if any(k.startswith("features.") for k in state_dict) else ""

    def conv(key: str) -> Dict[str, Array]:
        w = np.asarray(state_dict[f"{prefix}{key}.weight"])  # (O, I, kH, kW)
        b = np.asarray(state_dict[f"{prefix}{key}.bias"])
        return {"kernel": jnp.asarray(w.transpose(2, 3, 1, 0)), "bias": jnp.asarray(b)}

    params: Dict[str, Any] = {"conv0": conv("0")}
    for li in _FIRES:
        params[f"fire{li}"] = {
            "squeeze": conv(f"{li}.squeeze"),
            "expand1x1": conv(f"{li}.expand1x1"),
            "expand3x3": conv(f"{li}.expand3x3"),
        }
    return {"params": params}


def squeezenet_lpips_extractor(
    state_dict: Optional[Mapping[str, Any]] = None,
    variables: Optional[Dict[str, Any]] = None,
):
    """Build the ``feats_fn`` the LPIPS pipeline injects: NCHW in -> 7 NCHW slice maps."""
    if nn is None:  # pragma: no cover
        raise ModuleNotFoundError("flax is required for the built-in SqueezeNet extractor")
    model = SqueezeNetFeatures()
    if variables is None:
        if state_dict is not None:
            variables = from_torch_state_dict(state_dict)
        else:
            variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 64, 64), jnp.float32))

    def feats_fn(imgs: Array) -> List[Array]:
        outs = model.apply(variables, imgs)
        return [jnp.transpose(o, (0, 3, 1, 2)) for o in outs]

    return jax.jit(feats_fn)
