"""Flax VGG16 feature slices for LPIPS.

Mirrors the vendored ``Vgg16`` in the reference (``functional/image/lpips.py:134-187``):
five conv stages whose post-relu activations (relu1_2, relu2_2, relu3_3, relu4_3,
relu5_3) feed the LPIPS linear heads. ``from_torch_state_dict`` converts a torchvision
``vgg16().features`` checkpoint (layer-indexed keys ``features.N.weight``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

Array = jax.Array

# torchvision vgg16.features conv layer indices, grouped by stage
_STAGES: Tuple[Tuple[int, ...], ...] = ((0, 2), (5, 7), (10, 12, 14), (17, 19, 21), (24, 26, 28))
_WIDTHS: Tuple[int, ...] = (64, 128, 256, 512, 512)

# ImageNet normalisation baked into the LPIPS scaling layer (lpips.py:46-55)
_SHIFT = jnp.asarray([-0.030, -0.088, -0.188])
_SCALE = jnp.asarray([0.458, 0.448, 0.450])


if nn is not None:

    class VGG16Features(nn.Module):
        """``__call__`` maps NCHW/NHWC images -> 5 post-relu stage features (NHWC).

        ``apply_scaling=True`` applies the LPIPS ScalingLayer to raw [-1, 1] inputs;
        use ``False`` when composing with a pipeline that already scaled (the LPIPS
        functional pipeline applies ``scaling_layer`` itself).
        """

        apply_scaling: bool = True

        @nn.compact
        def __call__(self, x: Array) -> List[Array]:
            if x.shape[1] == 3 and x.shape[-1] != 3:  # NCHW -> NHWC
                x = jnp.transpose(x, (0, 2, 3, 1))
            if self.apply_scaling:
                x = (x - _SHIFT) / _SCALE  # LPIPS ScalingLayer on [-1, 1] inputs
            outs = []
            for si, stage in enumerate(_STAGES):
                for li in stage:
                    x = nn.Conv(_WIDTHS[si], (3, 3), padding=((1, 1), (1, 1)), name=f"conv{li}")(x)
                    x = nn.relu(x)
                outs.append(x)
                if si < len(_STAGES) - 1:
                    x = nn.max_pool(x, (2, 2), strides=(2, 2))
            return outs

else:  # pragma: no cover
    VGG16Features = None  # type: ignore[assignment,misc]


def from_torch_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Convert torchvision ``vgg16`` (or bare ``features``) weights to flax variables."""
    import numpy as np

    prefix = "features." if any(k.startswith("features.") for k in state_dict) else ""
    params: Dict[str, Any] = {}
    for stage in _STAGES:
        for li in stage:
            w = np.asarray(state_dict[f"{prefix}{li}.weight"])  # (O, I, 3, 3)
            b = np.asarray(state_dict[f"{prefix}{li}.bias"])
            params[f"conv{li}"] = {"kernel": jnp.asarray(w.transpose(2, 3, 1, 0)), "bias": jnp.asarray(b)}
    return {"params": params}


def vgg16_lpips_extractor(
    state_dict: Optional[Mapping[str, Any]] = None,
    variables: Optional[Dict[str, Any]] = None,
):
    """Build the ``feats_fn`` the LPIPS pipeline injects: NCHW in -> 5 NCHW stage maps.

    Drop-in for ``functional.image.lpips.make_lpips_net``: the pipeline applies the
    ScalingLayer itself, so scaling is disabled here, and outputs are returned NCHW
    (channel axis 1) as ``normalize_tensor``/the linear heads expect. Random init
    without weights — real topology/compile, meaningless LPIPS values until a
    torchvision checkpoint is converted in (nothing is bundled; zero egress).
    """
    if nn is None:  # pragma: no cover
        raise ModuleNotFoundError("flax is required for the built-in VGG16 extractor")
    model = VGG16Features(apply_scaling=False)
    if variables is None:
        if state_dict is not None:
            variables = from_torch_state_dict(state_dict)
        else:
            variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 64, 64), jnp.float32))

    def feats_fn(imgs: Array) -> List[Array]:
        outs = model.apply(variables, imgs)
        return [jnp.transpose(o, (0, 3, 1, 2)) for o in outs]

    return jax.jit(feats_fn)
