"""Flax InceptionV3 feature trunk for FID / KID / InceptionScore.

Mirrors the torchvision InceptionV3 topology the reference wraps via torch-fidelity
(``src/torchmetrics/image/fid.py:52-157``): BasicConv2d (conv + BN eps=1e-3 + relu),
Inception A/B/C/D/E blocks, global average pool to a 2048-d feature vector. Inference
only — BatchNorm applies stored statistics; no dropout, no aux head.

Built TPU-first: NHWC layout internally (XLA's preferred conv layout on TPU), bf16
compute with f32 statistics optional, the whole trunk jit-compiles to one XLA program.
``from_torch_state_dict`` converts a torchvision ``inception_v3`` checkpoint (OIHW ->
HWIO transposes, BN buffers); ``inception_v3_extractor`` packages params + apply into
the ``imgs -> (N, 2048)`` callable the image metrics accept.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

Array = jax.Array

_BN_EPS = 1e-3


if nn is not None:

    class BasicConv2d(nn.Module):
        """conv -> BN(eps=1e-3, inference) -> relu."""

        features: int
        kernel: Tuple[int, int]
        strides: Tuple[int, int] = (1, 1)
        padding: Any = (0, 0)

        @nn.compact
        def __call__(self, x: Array) -> Array:
            pad = self.padding
            if isinstance(pad, tuple) and isinstance(pad[0], int):
                pad = ((pad[0], pad[0]), (pad[1], pad[1]))
            x = nn.Conv(self.features, self.kernel, self.strides, padding=pad, use_bias=False, name="conv")(x)
            x = nn.BatchNorm(use_running_average=True, epsilon=_BN_EPS, name="bn")(x)
            return nn.relu(x)

    class InceptionA(nn.Module):
        pool_features: int

        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(64, (1, 1), name="branch1x1")(x)
            b5 = BasicConv2d(48, (1, 1), name="branch5x5_1")(x)
            b5 = BasicConv2d(64, (5, 5), padding=(2, 2), name="branch5x5_2")(b5)
            b3 = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
            b3 = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(b3)
            b3 = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_3")(b3)
            bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)))
            bp = BasicConv2d(self.pool_features, (1, 1), name="branch_pool")(bp)
            return jnp.concatenate([b1, b5, b3, bp], axis=-1)

    class InceptionB(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b3 = BasicConv2d(384, (3, 3), strides=(2, 2), name="branch3x3")(x)
            bd = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
            bd = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(bd)
            bd = BasicConv2d(96, (3, 3), strides=(2, 2), name="branch3x3dbl_3")(bd)
            bp = nn.max_pool(x, (3, 3), strides=(2, 2))
            return jnp.concatenate([b3, bd, bp], axis=-1)

    class InceptionC(nn.Module):
        channels_7x7: int

        @nn.compact
        def __call__(self, x: Array) -> Array:
            c7 = self.channels_7x7
            b1 = BasicConv2d(192, (1, 1), name="branch1x1")(x)
            b7 = BasicConv2d(c7, (1, 1), name="branch7x7_1")(x)
            b7 = BasicConv2d(c7, (1, 7), padding=(0, 3), name="branch7x7_2")(b7)
            b7 = BasicConv2d(192, (7, 1), padding=(3, 0), name="branch7x7_3")(b7)
            bd = BasicConv2d(c7, (1, 1), name="branch7x7dbl_1")(x)
            bd = BasicConv2d(c7, (7, 1), padding=(3, 0), name="branch7x7dbl_2")(bd)
            bd = BasicConv2d(c7, (1, 7), padding=(0, 3), name="branch7x7dbl_3")(bd)
            bd = BasicConv2d(c7, (7, 1), padding=(3, 0), name="branch7x7dbl_4")(bd)
            bd = BasicConv2d(192, (1, 7), padding=(0, 3), name="branch7x7dbl_5")(bd)
            bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)))
            bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
            return jnp.concatenate([b1, b7, bd, bp], axis=-1)

    class InceptionD(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b3 = BasicConv2d(192, (1, 1), name="branch3x3_1")(x)
            b3 = BasicConv2d(320, (3, 3), strides=(2, 2), name="branch3x3_2")(b3)
            b7 = BasicConv2d(192, (1, 1), name="branch7x7x3_1")(x)
            b7 = BasicConv2d(192, (1, 7), padding=(0, 3), name="branch7x7x3_2")(b7)
            b7 = BasicConv2d(192, (7, 1), padding=(3, 0), name="branch7x7x3_3")(b7)
            b7 = BasicConv2d(192, (3, 3), strides=(2, 2), name="branch7x7x3_4")(b7)
            bp = nn.max_pool(x, (3, 3), strides=(2, 2))
            return jnp.concatenate([b3, b7, bp], axis=-1)

    class InceptionE(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(320, (1, 1), name="branch1x1")(x)
            b3 = BasicConv2d(384, (1, 1), name="branch3x3_1")(x)
            b3a = BasicConv2d(384, (1, 3), padding=(0, 1), name="branch3x3_2a")(b3)
            b3b = BasicConv2d(384, (3, 1), padding=(1, 0), name="branch3x3_2b")(b3)
            b3 = jnp.concatenate([b3a, b3b], axis=-1)
            bd = BasicConv2d(448, (1, 1), name="branch3x3dbl_1")(x)
            bd = BasicConv2d(384, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(bd)
            bda = BasicConv2d(384, (1, 3), padding=(0, 1), name="branch3x3dbl_3a")(bd)
            bdb = BasicConv2d(384, (3, 1), padding=(1, 0), name="branch3x3dbl_3b")(bd)
            bd = jnp.concatenate([bda, bdb], axis=-1)
            bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)))
            bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
            return jnp.concatenate([b1, b3, bd, bp], axis=-1)

    class InceptionV3(nn.Module):
        """Feature trunk; ``__call__`` maps NCHW or NHWC uint8/float images -> (N, 2048)."""

        @nn.compact
        def __call__(self, x: Array) -> Array:
            if x.ndim != 4:
                raise ValueError(f"Expected 4d image batch, got shape {x.shape}")
            if x.shape[1] == 3 and x.shape[-1] != 3:  # NCHW -> NHWC
                x = jnp.transpose(x, (0, 2, 3, 1))
            if jnp.issubdtype(x.dtype, jnp.integer):
                x = x.astype(jnp.float32) / 255.0
            # torchvision's transform_input=False path: plain [0,1] -> [-1, 1]
            x = x * 2.0 - 1.0
            x = BasicConv2d(32, (3, 3), strides=(2, 2), name="Conv2d_1a_3x3")(x)
            x = BasicConv2d(32, (3, 3), name="Conv2d_2a_3x3")(x)
            x = BasicConv2d(64, (3, 3), padding=(1, 1), name="Conv2d_2b_3x3")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2))
            x = BasicConv2d(80, (1, 1), name="Conv2d_3b_1x1")(x)
            x = BasicConv2d(192, (3, 3), name="Conv2d_4a_3x3")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2))
            x = InceptionA(32, name="Mixed_5b")(x)
            x = InceptionA(64, name="Mixed_5c")(x)
            x = InceptionA(64, name="Mixed_5d")(x)
            x = InceptionB(name="Mixed_6a")(x)
            x = InceptionC(128, name="Mixed_6b")(x)
            x = InceptionC(160, name="Mixed_6c")(x)
            x = InceptionC(160, name="Mixed_6d")(x)
            x = InceptionC(192, name="Mixed_6e")(x)
            x = InceptionD(name="Mixed_7a")(x)
            x = InceptionE(name="Mixed_7b")(x)
            x = InceptionE(name="Mixed_7c")(x)
            return x.mean(axis=(1, 2))  # global average pool -> (N, 2048)

else:  # pragma: no cover
    InceptionV3 = None  # type: ignore[assignment,misc]


def _convert_basic_conv(src: Mapping[str, Any], prefix: str) -> Dict[str, Dict[str, Array]]:
    """torchvision ``BasicConv2d`` tensors -> flax {conv: {kernel}, bn: {...}}."""
    import numpy as np

    w = np.asarray(src[f"{prefix}.conv.weight"])  # (O, I, kH, kW)
    return {
        "conv": {"kernel": jnp.asarray(w.transpose(2, 3, 1, 0))},
        "bn": {
            "scale": jnp.asarray(np.asarray(src[f"{prefix}.bn.weight"])),
            "bias": jnp.asarray(np.asarray(src[f"{prefix}.bn.bias"])),
        },
    }


def _convert_basic_conv_stats(src: Mapping[str, Any], prefix: str) -> Dict[str, Dict[str, Array]]:
    import numpy as np

    return {
        "bn": {
            "mean": jnp.asarray(np.asarray(src[f"{prefix}.bn.running_mean"])),
            "var": jnp.asarray(np.asarray(src[f"{prefix}.bn.running_var"])),
        }
    }


_STEM = ["Conv2d_1a_3x3", "Conv2d_2a_3x3", "Conv2d_2b_3x3", "Conv2d_3b_1x1", "Conv2d_4a_3x3"]
_BLOCK_CONVS: Dict[str, Sequence[str]] = {
    "Mixed_5b": ["branch1x1", "branch5x5_1", "branch5x5_2", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3", "branch_pool"],
    "Mixed_6a": ["branch3x3", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3"],
    "Mixed_6b": ["branch1x1", "branch7x7_1", "branch7x7_2", "branch7x7_3", "branch7x7dbl_1", "branch7x7dbl_2", "branch7x7dbl_3", "branch7x7dbl_4", "branch7x7dbl_5", "branch_pool"],
    "Mixed_7a": ["branch3x3_1", "branch3x3_2", "branch7x7x3_1", "branch7x7x3_2", "branch7x7x3_3", "branch7x7x3_4"],
    "Mixed_7b": ["branch1x1", "branch3x3_1", "branch3x3_2a", "branch3x3_2b", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3a", "branch3x3dbl_3b", "branch_pool"],
}
_BLOCK_ALIASES = {
    "Mixed_5c": "Mixed_5b",
    "Mixed_5d": "Mixed_5b",
    "Mixed_6c": "Mixed_6b",
    "Mixed_6d": "Mixed_6b",
    "Mixed_6e": "Mixed_6b",
    "Mixed_7c": "Mixed_7b",
}
_ALL_BLOCKS = ["Mixed_5b", "Mixed_5c", "Mixed_5d", "Mixed_6a", "Mixed_6b", "Mixed_6c", "Mixed_6d", "Mixed_6e", "Mixed_7a", "Mixed_7b", "Mixed_7c"]


def from_torch_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Convert a torchvision ``inception_v3`` state dict to flax variables.

    Returns ``{"params": ..., "batch_stats": ...}`` ready for ``InceptionV3().apply``.
    Aux-head and fc keys are ignored.
    """
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    for name in _STEM:
        params[name] = _convert_basic_conv(state_dict, name)
        stats[name] = _convert_basic_conv_stats(state_dict, name)
    for block in _ALL_BLOCKS:
        layout = _BLOCK_CONVS[_BLOCK_ALIASES.get(block, block)]
        params[block] = {c: _convert_basic_conv(state_dict, f"{block}.{c}") for c in layout}
        stats[block] = {c: _convert_basic_conv_stats(state_dict, f"{block}.{c}") for c in layout}
    return {"params": params, "batch_stats": stats}


def inception_v3_extractor(
    state_dict: Optional[Mapping[str, Any]] = None,
    variables: Optional[Dict[str, Any]] = None,
    dtype: jnp.dtype = jnp.float32,
):
    """Build the ``imgs -> (N, 2048)`` callable the image metrics accept.

    Pass either a torch(vision) ``state_dict`` (converted here) or ready flax
    ``variables``. With neither, parameters are randomly initialised — shapes and the
    compiled graph are real, but FID values are meaningless until weights are loaded
    (no pretrained weights are bundled; the reference has the same failure mode when
    ``torch-fidelity`` is absent).
    """
    if nn is None:  # pragma: no cover
        raise ModuleNotFoundError("flax is required for the built-in InceptionV3 extractor")
    model = InceptionV3()
    if variables is None:
        if state_dict is not None:
            variables = from_torch_state_dict(state_dict)
        else:
            variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 299, 299), jnp.float32))

    def apply(imgs: Array) -> Array:
        # keep integer dtypes intact: the trunk's own uint8 -> /255 normalisation must
        # see them (casting first would skip it and feed [-1, 509] to the network)
        if not jnp.issubdtype(imgs.dtype, jnp.integer):
            imgs = imgs.astype(dtype)
        return model.apply(variables, imgs)

    return jax.jit(apply)
