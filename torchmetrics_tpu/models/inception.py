"""Flax InceptionV3 feature trunk for FID / KID / InceptionScore.

Mirrors the torchvision InceptionV3 topology the reference wraps via torch-fidelity
(``src/torchmetrics/image/fid.py:52-157``): BasicConv2d (conv + BN eps=1e-3 + relu),
Inception A/B/C/D/E blocks, global average pool to a 2048-d feature vector. Inference
only — BatchNorm applies stored statistics; no dropout, no aux head.

Built TPU-first: NHWC layout internally (XLA's preferred conv layout on TPU), bf16
compute with f32 statistics optional, the whole trunk jit-compiles to one XLA program.
``from_torch_state_dict`` converts a torchvision ``inception_v3`` checkpoint (OIHW ->
HWIO transposes, BN buffers); ``inception_v3_extractor`` packages params + apply into
the ``imgs -> (N, 2048)`` callable the image metrics accept.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

Array = jax.Array

_BN_EPS = 1e-3


if nn is not None:

    class BasicConv2d(nn.Module):
        """conv -> BN(eps=1e-3, inference) -> relu."""

        features: int
        kernel: Tuple[int, int]
        strides: Tuple[int, int] = (1, 1)
        padding: Any = (0, 0)

        @nn.compact
        def __call__(self, x: Array) -> Array:
            pad = self.padding
            if isinstance(pad, tuple) and isinstance(pad[0], int):
                pad = ((pad[0], pad[0]), (pad[1], pad[1]))
            x = nn.Conv(self.features, self.kernel, self.strides, padding=pad, use_bias=False, name="conv")(x)
            x = nn.BatchNorm(use_running_average=True, epsilon=_BN_EPS, name="bn")(x)
            return nn.relu(x)

    def _branch_avg_pool(x: Array, count_include_pad: bool) -> Array:
        """3x3/stride-1/pad-1 average pool; ``count_include_pad=False`` is the
        torch-fidelity FID-variant semantics (border windows divide by the number of
        real pixels, not 9)."""
        return nn.avg_pool(
            x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)), count_include_pad=count_include_pad
        )

    class InceptionA(nn.Module):
        pool_features: int
        fid_pool: bool = False  # torch-fidelity FIDInceptionA: count_include_pad=False

        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(64, (1, 1), name="branch1x1")(x)
            b5 = BasicConv2d(48, (1, 1), name="branch5x5_1")(x)
            b5 = BasicConv2d(64, (5, 5), padding=(2, 2), name="branch5x5_2")(b5)
            b3 = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
            b3 = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(b3)
            b3 = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_3")(b3)
            bp = _branch_avg_pool(x, count_include_pad=not self.fid_pool)
            bp = BasicConv2d(self.pool_features, (1, 1), name="branch_pool")(bp)
            return jnp.concatenate([b1, b5, b3, bp], axis=-1)

    class InceptionB(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b3 = BasicConv2d(384, (3, 3), strides=(2, 2), name="branch3x3")(x)
            bd = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
            bd = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(bd)
            bd = BasicConv2d(96, (3, 3), strides=(2, 2), name="branch3x3dbl_3")(bd)
            bp = nn.max_pool(x, (3, 3), strides=(2, 2))
            return jnp.concatenate([b3, bd, bp], axis=-1)

    class InceptionC(nn.Module):
        channels_7x7: int
        fid_pool: bool = False  # torch-fidelity FIDInceptionC: count_include_pad=False

        @nn.compact
        def __call__(self, x: Array) -> Array:
            c7 = self.channels_7x7
            b1 = BasicConv2d(192, (1, 1), name="branch1x1")(x)
            b7 = BasicConv2d(c7, (1, 1), name="branch7x7_1")(x)
            b7 = BasicConv2d(c7, (1, 7), padding=(0, 3), name="branch7x7_2")(b7)
            b7 = BasicConv2d(192, (7, 1), padding=(3, 0), name="branch7x7_3")(b7)
            bd = BasicConv2d(c7, (1, 1), name="branch7x7dbl_1")(x)
            bd = BasicConv2d(c7, (7, 1), padding=(3, 0), name="branch7x7dbl_2")(bd)
            bd = BasicConv2d(c7, (1, 7), padding=(0, 3), name="branch7x7dbl_3")(bd)
            bd = BasicConv2d(c7, (7, 1), padding=(3, 0), name="branch7x7dbl_4")(bd)
            bd = BasicConv2d(192, (1, 7), padding=(0, 3), name="branch7x7dbl_5")(bd)
            bp = _branch_avg_pool(x, count_include_pad=not self.fid_pool)
            bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
            return jnp.concatenate([b1, b7, bd, bp], axis=-1)

    class InceptionD(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b3 = BasicConv2d(192, (1, 1), name="branch3x3_1")(x)
            b3 = BasicConv2d(320, (3, 3), strides=(2, 2), name="branch3x3_2")(b3)
            b7 = BasicConv2d(192, (1, 1), name="branch7x7x3_1")(x)
            b7 = BasicConv2d(192, (1, 7), padding=(0, 3), name="branch7x7x3_2")(b7)
            b7 = BasicConv2d(192, (7, 1), padding=(3, 0), name="branch7x7x3_3")(b7)
            b7 = BasicConv2d(192, (3, 3), strides=(2, 2), name="branch7x7x3_4")(b7)
            bp = nn.max_pool(x, (3, 3), strides=(2, 2))
            return jnp.concatenate([b3, b7, bp], axis=-1)

    class InceptionE(nn.Module):
        # torch-fidelity variants: FIDInceptionE_1 (Mixed_7b) = avg pool with
        # count_include_pad=False; FIDInceptionE_2 (Mixed_7c) = MAX pool — the TF
        # implementation's quirk, preserved so converted weights reproduce scores.
        pool: str = "avg"  # "avg" | "fid_avg" | "max"

        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(320, (1, 1), name="branch1x1")(x)
            b3 = BasicConv2d(384, (1, 1), name="branch3x3_1")(x)
            b3a = BasicConv2d(384, (1, 3), padding=(0, 1), name="branch3x3_2a")(b3)
            b3b = BasicConv2d(384, (3, 1), padding=(1, 0), name="branch3x3_2b")(b3)
            b3 = jnp.concatenate([b3a, b3b], axis=-1)
            bd = BasicConv2d(448, (1, 1), name="branch3x3dbl_1")(x)
            bd = BasicConv2d(384, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(bd)
            bda = BasicConv2d(384, (1, 3), padding=(0, 1), name="branch3x3dbl_3a")(bd)
            bdb = BasicConv2d(384, (3, 1), padding=(1, 0), name="branch3x3dbl_3b")(bd)
            bd = jnp.concatenate([bda, bdb], axis=-1)
            if self.pool == "max":
                bp = nn.max_pool(
                    jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), constant_values=-jnp.inf),
                    (3, 3),
                    strides=(1, 1),
                )
            else:
                bp = _branch_avg_pool(x, count_include_pad=self.pool == "avg")
            bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
            return jnp.concatenate([b1, b3, bd, bp], axis=-1)

    class InceptionV3(nn.Module):
        """Feature trunk; ``__call__`` maps NCHW or NHWC uint8/float images -> (N, 2048)."""

        @nn.compact
        def __call__(self, x: Array) -> Array:
            if x.ndim != 4:
                raise ValueError(f"Expected 4d image batch, got shape {x.shape}")
            if x.shape[1] == 3 and x.shape[-1] != 3:  # NCHW -> NHWC
                x = jnp.transpose(x, (0, 2, 3, 1))
            if jnp.issubdtype(x.dtype, jnp.integer):
                x = x.astype(jnp.float32) / 255.0
            # torchvision's transform_input=False path: plain [0,1] -> [-1, 1]
            x = x * 2.0 - 1.0
            x = BasicConv2d(32, (3, 3), strides=(2, 2), name="Conv2d_1a_3x3")(x)
            x = BasicConv2d(32, (3, 3), name="Conv2d_2a_3x3")(x)
            x = BasicConv2d(64, (3, 3), padding=(1, 1), name="Conv2d_2b_3x3")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2))
            x = BasicConv2d(80, (1, 1), name="Conv2d_3b_1x1")(x)
            x = BasicConv2d(192, (3, 3), name="Conv2d_4a_3x3")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2))
            x = InceptionA(32, name="Mixed_5b")(x)
            x = InceptionA(64, name="Mixed_5c")(x)
            x = InceptionA(64, name="Mixed_5d")(x)
            x = InceptionB(name="Mixed_6a")(x)
            x = InceptionC(128, name="Mixed_6b")(x)
            x = InceptionC(160, name="Mixed_6c")(x)
            x = InceptionC(160, name="Mixed_6d")(x)
            x = InceptionC(192, name="Mixed_6e")(x)
            x = InceptionD(name="Mixed_7a")(x)
            x = InceptionE(name="Mixed_7b")(x)
            x = InceptionE(name="Mixed_7c")(x)
            return x.mean(axis=(1, 2))  # global average pool -> (N, 2048)

    class FIDInceptionV3(nn.Module):
        """torch-fidelity's 'inception-v3-compat' trunk (reference ``image/fid.py:69-153``).

        Differences from torchvision captured here: TF1-style bilinear resize to
        299x299 (``align_corners=False``, source = dest * scale — implemented as two
        matmuls, MXU-friendly), ``(x - 128) / 128`` input normalisation, FID-variant
        pooling in the A/C/E blocks (``count_include_pad=False``; max pool in
        Mixed_7c), and a 1008-way fc head. ``request`` picks the returned taps from
        {'64', '192', '768', '2048', 'logits_unbiased', 'logits'}.
        """

        request: Tuple[str, ...] = ("2048",)

        @nn.compact
        def __call__(self, x: Array) -> Dict[str, Array]:
            if x.ndim != 4:
                raise ValueError(f"Expected 4d image batch, got shape {x.shape}")
            if x.shape[1] == 3 and x.shape[-1] != 3:  # NCHW -> NHWC
                x = jnp.transpose(x, (0, 2, 3, 1))
            x = x.astype(jnp.float32)
            x = tf1_bilinear_resize(x, (299, 299))
            x = (x - 128.0) / 128.0

            out: Dict[str, Array] = {}
            need = set(self.request)

            x = BasicConv2d(32, (3, 3), strides=(2, 2), name="Conv2d_1a_3x3")(x)
            x = BasicConv2d(32, (3, 3), name="Conv2d_2a_3x3")(x)
            x = BasicConv2d(64, (3, 3), padding=(1, 1), name="Conv2d_2b_3x3")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2))
            if "64" in need:
                out["64"] = x.mean(axis=(1, 2))
            x = BasicConv2d(80, (1, 1), name="Conv2d_3b_1x1")(x)
            x = BasicConv2d(192, (3, 3), name="Conv2d_4a_3x3")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2))
            if "192" in need:
                out["192"] = x.mean(axis=(1, 2))
            x = InceptionA(32, fid_pool=True, name="Mixed_5b")(x)
            x = InceptionA(64, fid_pool=True, name="Mixed_5c")(x)
            x = InceptionA(64, fid_pool=True, name="Mixed_5d")(x)
            x = InceptionB(name="Mixed_6a")(x)
            x = InceptionC(128, fid_pool=True, name="Mixed_6b")(x)
            x = InceptionC(160, fid_pool=True, name="Mixed_6c")(x)
            x = InceptionC(160, fid_pool=True, name="Mixed_6d")(x)
            x = InceptionC(192, fid_pool=True, name="Mixed_6e")(x)
            if "768" in need:
                out["768"] = x.mean(axis=(1, 2))
            x = InceptionD(name="Mixed_7a")(x)
            x = InceptionE(pool="fid_avg", name="Mixed_7b")(x)
            x = InceptionE(pool="max", name="Mixed_7c")(x)
            x = x.mean(axis=(1, 2))  # (N, 2048)
            if "2048" in need:
                out["2048"] = x
            if need & {"logits_unbiased", "logits"}:
                kernel = self.param("fc_kernel", nn.initializers.lecun_normal(), (2048, 1008))
                bias = self.param("fc_bias", nn.initializers.zeros_init(), (1008,))
                unbiased = x @ kernel
                if "logits_unbiased" in need:
                    out["logits_unbiased"] = unbiased
                if "logits" in need:
                    out["logits"] = unbiased + bias
            return out

else:  # pragma: no cover
    InceptionV3 = None  # type: ignore[assignment,misc]
    FIDInceptionV3 = None  # type: ignore[assignment,misc]


def tf1_bilinear_resize(x: Array, out_hw: Tuple[int, int]) -> Array:
    """Bilinear resize with TF1 ``align_corners=False`` semantics, as two matmuls.

    torch-fidelity's ``interpolate_bilinear_2d_like_tensorflow1x`` maps source
    coordinates as ``src = dst * (in/out)`` (no half-pixel offset — unlike
    ``jax.image.resize``). Expressed as per-axis interpolation matrices so the whole
    resize rides the MXU instead of gather lanes. Input/output NHWC.
    """
    in_h, in_w = x.shape[1], x.shape[2]
    mh = _tf1_resize_matrix(in_h, out_hw[0])
    mw = _tf1_resize_matrix(in_w, out_hw[1])
    x = jnp.einsum("oh,nhwc->nowc", mh, x)
    return jnp.einsum("pw,nowc->nopc", mw, x)


def _tf1_resize_matrix(in_size: int, out_size: int) -> Array:
    scale = in_size / out_size
    src = jnp.arange(out_size, dtype=jnp.float32) * scale
    x0 = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, in_size - 1)
    x1 = jnp.minimum(x0 + 1, in_size - 1)
    frac = src - x0.astype(jnp.float32)
    rows = jnp.arange(out_size)
    m = jnp.zeros((out_size, in_size), jnp.float32)
    m = m.at[rows, x0].add(1.0 - frac)
    m = m.at[rows, x1].add(frac)
    return m


def _convert_basic_conv(src: Mapping[str, Any], prefix: str) -> Dict[str, Dict[str, Array]]:
    """torchvision ``BasicConv2d`` tensors -> flax {conv: {kernel}, bn: {...}}."""
    import numpy as np

    w = np.asarray(src[f"{prefix}.conv.weight"])  # (O, I, kH, kW)
    return {
        "conv": {"kernel": jnp.asarray(w.transpose(2, 3, 1, 0))},
        "bn": {
            "scale": jnp.asarray(np.asarray(src[f"{prefix}.bn.weight"])),
            "bias": jnp.asarray(np.asarray(src[f"{prefix}.bn.bias"])),
        },
    }


def _convert_basic_conv_stats(src: Mapping[str, Any], prefix: str) -> Dict[str, Dict[str, Array]]:
    import numpy as np

    return {
        "bn": {
            "mean": jnp.asarray(np.asarray(src[f"{prefix}.bn.running_mean"])),
            "var": jnp.asarray(np.asarray(src[f"{prefix}.bn.running_var"])),
        }
    }


_STEM = ["Conv2d_1a_3x3", "Conv2d_2a_3x3", "Conv2d_2b_3x3", "Conv2d_3b_1x1", "Conv2d_4a_3x3"]
_BLOCK_CONVS: Dict[str, Sequence[str]] = {
    "Mixed_5b": ["branch1x1", "branch5x5_1", "branch5x5_2", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3", "branch_pool"],
    "Mixed_6a": ["branch3x3", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3"],
    "Mixed_6b": ["branch1x1", "branch7x7_1", "branch7x7_2", "branch7x7_3", "branch7x7dbl_1", "branch7x7dbl_2", "branch7x7dbl_3", "branch7x7dbl_4", "branch7x7dbl_5", "branch_pool"],
    "Mixed_7a": ["branch3x3_1", "branch3x3_2", "branch7x7x3_1", "branch7x7x3_2", "branch7x7x3_3", "branch7x7x3_4"],
    "Mixed_7b": ["branch1x1", "branch3x3_1", "branch3x3_2a", "branch3x3_2b", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3a", "branch3x3dbl_3b", "branch_pool"],
}
_BLOCK_ALIASES = {
    "Mixed_5c": "Mixed_5b",
    "Mixed_5d": "Mixed_5b",
    "Mixed_6c": "Mixed_6b",
    "Mixed_6d": "Mixed_6b",
    "Mixed_6e": "Mixed_6b",
    "Mixed_7c": "Mixed_7b",
}
_ALL_BLOCKS = ["Mixed_5b", "Mixed_5c", "Mixed_5d", "Mixed_6a", "Mixed_6b", "Mixed_6c", "Mixed_6d", "Mixed_6e", "Mixed_7a", "Mixed_7b", "Mixed_7c"]


def from_torch_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Convert a torchvision ``inception_v3`` state dict to flax variables.

    Returns ``{"params": ..., "batch_stats": ...}`` ready for ``InceptionV3().apply``.
    Aux-head and fc keys are ignored.
    """
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    for name in _STEM:
        params[name] = _convert_basic_conv(state_dict, name)
        stats[name] = _convert_basic_conv_stats(state_dict, name)
    for block in _ALL_BLOCKS:
        layout = _BLOCK_CONVS[_BLOCK_ALIASES.get(block, block)]
        params[block] = {c: _convert_basic_conv(state_dict, f"{block}.{c}") for c in layout}
        stats[block] = {c: _convert_basic_conv_stats(state_dict, f"{block}.{c}") for c in layout}
    return {"params": params, "batch_stats": stats}


def from_fidelity_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Convert a torch-fidelity ``pt_inception-2015-12-05`` state dict to flax variables.

    The checkpoint uses torchvision-style module names plus a 1008-way ``fc``; block
    conv layout is identical, so the torchvision converters apply, with the fc mapped
    to the ``FIDInceptionV3`` flat params.
    """
    import numpy as np

    variables = from_torch_state_dict(state_dict)
    if "fc.weight" in state_dict:
        w = np.asarray(state_dict["fc.weight"])  # (1008, 2048)
        variables["params"]["fc_kernel"] = jnp.asarray(w.T)
        variables["params"]["fc_bias"] = jnp.asarray(np.asarray(state_dict["fc.bias"]))
    return variables


def fid_inception_v3_extractor(
    request: Union[str, Sequence[str]] = "2048",
    state_dict: Optional[Mapping[str, Any]] = None,
    variables: Optional[Dict[str, Any]] = None,
    allow_random: bool = False,
):
    """Build the torch-fidelity-compat ``imgs -> (N, d)`` callable for FID/KID/IS.

    ``request`` is one tap name or a sequence of them (a single name returns that
    array; a sequence returns a tuple in order). Without ``state_dict``/``variables``
    this RAISES unless ``allow_random=True`` — mirroring the reference's hard error
    when torch-fidelity is absent (``image/fid.py:264-270``), because a
    randomly-initialised trunk produces plausible-looking but non-canonical scores.
    With ``allow_random=True`` the trunk is deterministically randomly initialised
    and warns: scores are then self-consistent (valid for tracking relative progress
    within one configuration) but NOT comparable to canonical torch-fidelity/reference
    FID values. Convert the ``pt_inception-2015-12-05`` checkpoint via
    ``from_fidelity_state_dict`` for canonical scores.
    """
    if nn is None:  # pragma: no cover
        raise ModuleNotFoundError("flax is required for the built-in InceptionV3 extractor")
    single = isinstance(request, str)
    taps = (request,) if single else tuple(request)
    valid = {"64", "192", "768", "2048", "logits_unbiased", "logits"}
    if not set(taps) <= valid:
        raise ValueError(f"Requested taps {taps} must be a subset of {sorted(valid)}")
    if variables is None:
        if state_dict is not None:
            variables = from_fidelity_state_dict(state_dict)
        else:
            if not allow_random:
                raise RuntimeError(
                    "No pretrained InceptionV3 weights were supplied and none are bundled (zero-egress"
                    " environment), so FID/KID/IS scores would come from a randomly-initialised trunk —"
                    " plausible-looking but meaningless. Pass `state_dict=` (a torch-fidelity"
                    " pt_inception-2015-12-05 checkpoint, converted via `from_fidelity_state_dict`) or"
                    " `variables=` for canonical scores, or opt in to the random trunk explicitly with"
                    " `allow_random_features=True` (metric constructors) / `allow_random=True` (this builder)."
                )
            from torchmetrics_tpu.utilities.prints import rank_zero_warn

            rank_zero_warn(
                "Using a deterministic randomly-initialised FID-compat trunk (`allow_random=True`): scores"
                " are self-consistent but NOT comparable to canonical FID/KID/IS values."
            )
            # cached: FID + KID + IS with default args share one trunk + XLA cache
            return _default_fid_extractor(taps)

    model = FIDInceptionV3(request=taps)

    def apply(imgs: Array):
        out = model.apply(variables, imgs)
        return out[taps[0]] if single else tuple(out[t] for t in taps)

    return jax.jit(apply)


@lru_cache(maxsize=None)
def _default_fid_extractor(taps: Tuple[str, ...]):
    """One deterministic random-init trunk + jit cache per requested tap set."""
    model = FIDInceptionV3(request=taps)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32), jnp.float32))
    single = len(taps) == 1

    def apply(imgs: Array):
        out = model.apply(variables, imgs)
        return out[taps[0]] if single else tuple(out[t] for t in taps)

    return jax.jit(apply)


def inception_v3_extractor(
    state_dict: Optional[Mapping[str, Any]] = None,
    variables: Optional[Dict[str, Any]] = None,
    dtype: jnp.dtype = jnp.float32,
):
    """Build the ``imgs -> (N, 2048)`` callable the image metrics accept.

    Pass either a torch(vision) ``state_dict`` (converted here) or ready flax
    ``variables``. With neither, parameters are randomly initialised — shapes and the
    compiled graph are real, but FID values are meaningless until weights are loaded
    (no pretrained weights are bundled; the reference has the same failure mode when
    ``torch-fidelity`` is absent).
    """
    if nn is None:  # pragma: no cover
        raise ModuleNotFoundError("flax is required for the built-in InceptionV3 extractor")
    model = InceptionV3()
    if variables is None:
        if state_dict is not None:
            variables = from_torch_state_dict(state_dict)
        else:
            variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 299, 299), jnp.float32))

    def apply(imgs: Array) -> Array:
        # keep integer dtypes intact: the trunk's own uint8 -> /255 normalisation must
        # see them (casting first would skip it and feed [-1, 509] to the network)
        if not jnp.issubdtype(imgs.dtype, jnp.integer):
            imgs = imgs.astype(dtype)
        return model.apply(variables, imgs)

    return jax.jit(apply)
