"""Generate docs/api/*.md symbol listings from the live package exports."""

import importlib
import inspect
import os
import pathlib

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402  (the axon sitecustomize overrides the env var; pin the config)

jax.config.update("jax_platforms", "cpu")

DOMAINS = [
    ("torchmetrics_tpu", "Root exports"),
    ("torchmetrics_tpu.functional", "Functional API"),
    ("torchmetrics_tpu.classification", "Classification"),
    ("torchmetrics_tpu.regression", "Regression"),
    ("torchmetrics_tpu.image", "Image"),
    ("torchmetrics_tpu.text", "Text"),
    ("torchmetrics_tpu.audio", "Audio"),
    ("torchmetrics_tpu.detection", "Detection"),
    ("torchmetrics_tpu.retrieval", "Retrieval"),
    ("torchmetrics_tpu.nominal", "Nominal"),
    ("torchmetrics_tpu.multimodal", "Multimodal"),
    ("torchmetrics_tpu.wrappers", "Wrappers"),
    ("torchmetrics_tpu.serve", "Serving / streaming"),
    ("torchmetrics_tpu.ops", "TPU compute kernels"),
    ("torchmetrics_tpu.models", "Feature-extractor models"),
    ("torchmetrics_tpu.parallel", "Parallel / sync"),
]

OUT = pathlib.Path(__file__).parent / "api"


def first_line(obj) -> str:
    doc = inspect.getdoc(obj) or ""
    return doc.split("\n")[0].strip()


def main() -> None:
    OUT.mkdir(exist_ok=True)
    index = ["# API reference", ""]
    for mod_name, title in DOMAINS:
        mod = importlib.import_module(mod_name)
        names = sorted(set(getattr(mod, "__all__", dir(mod))))
        lines = [f"# {title} (`{mod_name}`)", ""]
        n_symbols = 0
        for name in names:
            if name.startswith("_"):
                continue
            obj = getattr(mod, name, None)
            if obj is None:
                continue
            kind = "class" if inspect.isclass(obj) else "function" if callable(obj) else "object"
            desc = first_line(obj)
            lines.append(f"- **`{name}`** ({kind}) — {desc}" if desc else f"- **`{name}`** ({kind})")
            n_symbols += 1
        slug = mod_name.replace("torchmetrics_tpu", "root").replace(".", "_")
        (OUT / f"{slug}.md").write_text("\n".join(lines) + "\n")
        index.append(f"- [{title}]({slug}.md) — {n_symbols} symbols")
    (OUT / "index.md").write_text("\n".join(index) + "\n")
    print(f"wrote {len(DOMAINS) + 1} files to {OUT}")


if __name__ == "__main__":
    main()
