# One-command entry points (reference Makefile:22-26 analogue).

.PHONY: test test-fast bench multichip lint lint-json

test:            ## full gate: CPU-mesh suite + doctests + differential + distributed worlds
	bash scripts/ci.sh

lint:            ## static invariant analysis (tools/tmlint): transfer purity, knob/counter/event lockstep, lock discipline
	python -m tools.tmlint torchmetrics_tpu/

lint-json:       ## same, machine-readable (per-rule finding counts for trend tooling)
	python -m tools.tmlint torchmetrics_tpu/ --json

test-fast:       ## same gate minus the execute-the-reference differential sweep
	bash scripts/ci.sh fast

bench:           ## one JSON line on the current accelerator
	python bench.py

multichip:       ## compile-check the sharded path on an 8-virtual-device CPU mesh
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('multichip OK')"
