"""TM5xx — event taxonomy: every recorded kind is declared and documented.

The flight-recorder ``kind`` strings are the join key for every downstream
consumer — per-kind counts, the chrome-trace renderer, the
``tm_tpu_events_total`` labels, the counter gates' event assertions. A typo'd
kind silently forks the taxonomy. Rules:

- **TM501 unknown-event-kind** — a literal kind at a ``record(...)`` site
  (including ``A if cond else B`` literal pairs) that is not declared in
  ``diag/trace.py``'s ``EVENT_KINDS``.
- **TM502 dynamic-event-kind** — a non-literal kind expression at a record
  site, outside functions annotated ``# tmlint: event-forwarder`` (the
  declared pass-through helpers).
- **TM503 event-kind-undocumented** — an ``EVENT_KINDS`` member missing from
  the taxonomy table in ``docs/pages/observability.md``.
- **TM504 event-kind-orphan** — an ``EVENT_KINDS`` member no call site in the
  analyzed tree records (dead taxonomy: the declaration outlived the code).

Record sites are recognized by receiver: an alias of ``diag.trace``
(``_diag.record`` / ``trace.record``), a bare ``record`` imported from it, or
a local bound from ``active_recorder()`` / ``diag_context(...) as rec``.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence, Set

from tools.tmlint.core import Finding, Project, SourceFile
from tools.tmlint.registries import docs_text, event_kinds

_TRACE_REL = "torchmetrics_tpu/diag/trace.py"
_DOCS_REL = "docs/pages/observability.md"


def _trace_aliases(sf: SourceFile) -> Set[str]:
    """Names in this module that refer to the diag.trace module or its record."""
    aliases: Set[str] = set()
    bare_record = False
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if a.name == "trace" and mod.endswith("diag"):
                    aliases.add(a.asname or a.name)
                if a.name == "record" and mod.endswith("trace"):
                    bare_record = True
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("diag.trace"):
                    aliases.add((a.asname or a.name).split(".")[0])
    if bare_record:
        aliases.add("<bare>")
    return aliases


def _recorder_locals(sf: SourceFile) -> Set[str]:
    """Names bound from active_recorder() / diag_context(...) as X."""
    out: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (fn.id if isinstance(fn, ast.Name) else None)
            if name == "active_recorder":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    fn = expr.func
                    name = fn.attr if isinstance(fn, ast.Attribute) else (fn.id if isinstance(fn, ast.Name) else None)
                    if name == "diag_context" and isinstance(item.optional_vars, ast.Name):
                        out.add(item.optional_vars.id)
    return out


def _kind_literals(expr: ast.expr) -> Optional[Sequence[str]]:
    """The literal kind(s) this expression can evaluate to, or None."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,)
    if isinstance(expr, ast.IfExp):
        a = _kind_literals(expr.body)
        b = _kind_literals(expr.orelse)
        if a is not None and b is not None:
            return tuple(a) + tuple(b)
    return None


def check_file(project: Project, sf: SourceFile) -> List[Finding]:
    rel = sf.relpath
    if rel == _TRACE_REL:  # the definitional module (record() itself)
        return []
    in_package = rel.startswith("torchmetrics_tpu/")
    if not in_package and "events" not in sf.scopes:
        return []
    kinds = event_kinds(project)
    if not kinds:
        return []
    aliases = _trace_aliases(sf)
    rec_names = _recorder_locals(sf)
    findings: List[Finding] = []

    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        is_site = False
        if isinstance(fn, ast.Attribute) and fn.attr == "record" and isinstance(fn.value, ast.Name):
            is_site = fn.value.id in aliases or fn.value.id in rec_names
        elif isinstance(fn, ast.Name) and fn.id == "record" and "<bare>" in aliases:
            is_site = True
        if not is_site:
            continue
        info = sf.enclosing_function(node)
        literals = _kind_literals(node.args[0])
        if literals is None:
            if (info is not None and info.event_forwarder) or sf.suppressed("TM502", node.lineno):
                continue
            findings.append(
                Finding(
                    "TM502", rel, node.lineno,
                    "non-literal event kind at a record() site — record literal kinds"
                    " from EVENT_KINDS, or annotate the declared pass-through helper"
                    " with # tmlint: event-forwarder",
                )
            )
            continue
        for kind in literals:
            if in_package:
                project.recorded_kinds.add(kind)
            if kind not in kinds and not sf.suppressed("TM501", node.lineno):
                findings.append(
                    Finding(
                        "TM501", rel, node.lineno,
                        f"event kind {kind!r} is not declared in diag/trace.py"
                        " EVENT_KINDS — declare it there and document it in"
                        f" {_DOCS_REL}",
                    )
                )
    return findings


def _documented_kinds(text: str) -> Set[str]:
    """Exact kind tokens the docs mention, with the table's
    ``a.trace/retrace`` shorthand rows expanded.

    Exact-token matching on purpose: a raw substring test would count
    ``update.scan`` as documented merely because ``update.scan.trace`` is —
    deleting a kind's own row must fail the lockstep.
    """
    out: Set[str] = set()
    # dotted tokens (optionally slash-expanded) anywhere in the text
    for m in re.finditer(r"[a-z_]+(?:\.[a-z_]+)+(?:/[a-z_.]+)*", text):
        token = m.group(0)
        parts = token.split("/")
        out.add(parts[0])
        prefix = parts[0].rsplit(".", 1)[0]
        for alt in parts[1:]:
            out.add(alt if "." in alt and alt.split(".")[0] == prefix.split(".")[0] else f"{prefix}.{alt}")
            out.add(f"{prefix}.{alt}")
    # single-word kinds (`collective`, `fallback`) appear as backticked tokens
    for m in re.finditer(r"`([a-z_]+)`", text):
        out.add(m.group(1))
    return out


def check_project(project: Project) -> List[Finding]:
    kinds = event_kinds(project)
    if not kinds:
        return []
    findings: List[Finding] = []
    text = docs_text(project, _DOCS_REL)
    if text is not None:
        documented = _documented_kinds(text)
        for kind in sorted(kinds):
            if kind not in documented:
                findings.append(
                    Finding(
                        "TM503", _TRACE_REL, 1,
                        f"event kind {kind!r} is declared but undocumented — add it to"
                        f" the taxonomy table in {_DOCS_REL}",
                    )
                )
    if project.full_package and project.recorded_kinds:
        for kind in sorted(kinds - project.recorded_kinds):
            findings.append(
                Finding(
                    "TM504", _TRACE_REL, 1,
                    f"event kind {kind!r} is declared in EVENT_KINDS but no analyzed"
                    " call site records it — drop the dead taxonomy entry",
                )
            )
    return findings
