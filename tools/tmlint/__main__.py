"""tmlint CLI — ``python -m tools.tmlint [paths...]``.

Exit status 0 when every finding is covered by the committed baseline
(``tools/tmlint/baseline.json`` by default), 1 otherwise. ``--json`` emits a
machine-readable report (per-rule counts included) for trend tooling like
``scripts/bench_trend.py``; ``--write-baseline`` grandfathers the current
findings (the committed baseline ships EMPTY for the transfer/knob/rider
families — keep it that way).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

from tools.tmlint import RULES, run_lint
from tools.tmlint.core import save_baseline


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.tmlint",
        description="AST-based invariant analyzer for torchmetrics_tpu",
    )
    parser.add_argument("paths", nargs="*", default=["torchmetrics_tpu"], help="files/dirs to analyze")
    parser.add_argument("--project-root", default=".", help="repo root (registries + docs live here)")
    parser.add_argument("--baseline", default=None, help="baseline file (default: tools/tmlint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true", help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true", help="write current findings to the baseline")
    parser.add_argument("--rules", default=None, help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--json", action="store_true", dest="as_json", help="machine-readable output")
    parser.add_argument("--list-rules", action="store_true", help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    root = Path(args.project_root).resolve()
    baseline = None
    if not args.no_baseline:
        baseline = Path(args.baseline) if args.baseline else root / "tools" / "tmlint" / "baseline.json"
    rules = {r.strip() for r in args.rules.split(",")} if args.rules else None
    paths = [Path(p) for p in args.paths]

    result = run_lint(paths, root=root, rules=rules, baseline_path=baseline)

    if args.write_baseline:
        target = baseline or root / "tools" / "tmlint" / "baseline.json"
        save_baseline(target, result["findings"])
        print(f"tmlint: wrote {len(result['findings'])} finding(s) to {target}")
        return 0

    counts = Counter(f.rule for f in result["new"])
    if args.as_json:
        print(
            json.dumps(
                {
                    "findings": [
                        {"rule": f.rule, "path": f.path, "line": f.line, "message": f.message}
                        for f in result["new"]
                    ],
                    "counts": {k: counts[k] for k in sorted(counts)},
                    "baselined": len(result["baselined"]),
                    "stale_baseline": result["stale"],
                    "ok": not result["new"],
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        for f in result["new"]:
            print(f.render())
        if result["baselined"]:
            print(f"tmlint: {len(result['baselined'])} grandfathered finding(s) suppressed by the baseline")
        for fp in result["stale"]:
            print(f"tmlint: stale baseline entry (fixed? regenerate): {fp}")
        if result["new"]:
            print(f"tmlint: {len(result['new'])} finding(s) [" + ", ".join(f"{k}={counts[k]}" for k in sorted(counts)) + "]")
        else:
            print("tmlint: clean")
    return 1 if result["new"] else 0


if __name__ == "__main__":
    sys.exit(main())
