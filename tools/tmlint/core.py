"""tmlint core — source model, annotations, suppressions, baseline, runner.

The analyzer is stdlib-only (``ast`` + ``tokenize``) and runs from source
text: no imports of the analyzed package, no accelerator, no test run. Every
rule reads the same :class:`SourceFile` model:

- **Suppressions** — ``# tmlint: disable=TM101`` (comma-separated rule ids)
  on the finding's line or the line directly above silences exactly those
  rules for that line.
- **Function annotations** — a comment on the ``def`` line or up to two lines
  above it:

  - ``# tmlint: holds(<lock>)`` — every caller guarantees ``<lock>`` is held
    for the duration (the ``*_locked`` convention, checked at the call sites'
    discipline, declared here);
  - ``# tmlint: single-owner(<role>)`` — the function runs on exactly one
    thread (``caller`` / ``worker``); guarded attributes may be touched
    without the lock;
  - ``# tmlint: boundary(<label>)`` — the function only runs inside the named
    sanctioned transfer boundary (label must be registered in
    ``diag/transfer_guard.py``);
  - ``# tmlint: host-only`` — the function operates on host (numpy/python)
    data exclusively; no device buffer can reach its readback calls;
  - ``# tmlint: event-forwarder`` — the function forwards a caller-supplied
    event kind (exempt from the dynamic-kind rule).

- **Attribute guards** — ``# guarded-by: <lock>`` trailing (or directly
  above) an attribute's declaring assignment marks it as lock-protected
  shared state; rule TM601 then requires every access to sit inside a
  ``with <lock>`` block, a ``holds(<lock>)`` function, or a single-owner
  function.

- **Scope markers** — ``# tmlint: scope=transfer|locks|knobs`` anywhere in a
  file opts it into the scoped rule families (used by test fixtures; in-tree
  scoping is path-based).

Findings carry a content-addressed ``fingerprint`` (rule + relative path +
normalized line text + occurrence index) so the committed baseline survives
unrelated line-number drift.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

_DISABLE_RE = re.compile(r"tmlint:\s*disable=([A-Z0-9, ]+)")
_ANNOT_RE = re.compile(
    r"tmlint:\s*(holds|single-owner|boundary)\(([^)]*)\)|tmlint:\s*(host-only|event-forwarder)"
)
_SCOPE_RE = re.compile(r"tmlint:\s*scope=([a-z,]+)")
_GUARDED_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # project-root-relative, posix separators
    line: int
    message: str
    fingerprint: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class FunctionInfo:
    node: ast.AST
    qualname: str
    holds: Set[str] = field(default_factory=set)
    single_owner: Optional[str] = None
    boundary: Optional[str] = None
    host_only: bool = False
    event_forwarder: bool = False


class SourceFile:
    """Parsed source + comment-derived metadata for one file."""

    def __init__(self, path: Path, root: Path) -> None:
        self.path = path
        self.relpath = path.resolve().relative_to(root.resolve()).as_posix() if _is_under(path, root) else path.as_posix()
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        self.comments: Dict[int, str] = self._collect_comments()
        self.scopes: Set[str] = self._collect_scopes()
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self.functions: Dict[ast.AST, FunctionInfo] = self._collect_functions()
        #: instance attributes, keyed by bare attr name (file-wide: subclasses
        #: inherit the base class's discipline) -> lock name
        self.guarded_attrs: Dict[str, str] = {}
        #: module-level globals -> lock name
        self.guarded_globals: Dict[str, str] = {}
        self.guard_decl_lines: Set[int] = set()
        self._collect_guards()

    # -- comments ------------------------------------------------------

    def _collect_comments(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass
        return out

    def _collect_scopes(self) -> Set[str]:
        scopes: Set[str] = set()
        for text in self.comments.values():
            m = _SCOPE_RE.search(text)
            if m:
                scopes.update(s for s in m.group(1).split(",") if s)
        return scopes

    def suppressed(self, rule: str, lineno: int) -> bool:
        """Same-line suppression, or anywhere in the contiguous comment block
        directly above (multi-line justifications are encouraged)."""
        candidates = [lineno]
        ln = lineno - 1
        while ln in self.comments:
            candidates.append(ln)
            ln -= 1
        for ln in candidates:
            m = _DISABLE_RE.search(self.comments.get(ln, ""))
            if m and rule in {r.strip() for r in m.group(1).split(",")}:
                return True
        return False

    # -- structure -----------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def enclosing(self, node: ast.AST, kinds: Tuple[type, ...]) -> Optional[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = self._parents.get(cur)
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[FunctionInfo]:
        fn = self.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        return self.functions.get(fn) if fn is not None else None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        found = self.enclosing(node, (ast.ClassDef,))
        return found if isinstance(found, ast.ClassDef) else None

    def qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(cur.name)
            cur = self._parents.get(cur)
        return ".".join(reversed(parts))

    def _collect_functions(self) -> Dict[ast.AST, FunctionInfo]:
        out: Dict[ast.AST, FunctionInfo] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            info = FunctionInfo(node=node, qualname=self.qualname(node))
            # the def line, any decorator lines, and the whole contiguous
            # comment block directly above them
            first = min([node.lineno] + [d.lineno for d in node.decorator_list])
            parts = [self.comments.get(node.lineno, "")]
            ln = first - 1
            while ln in self.comments:
                parts.append(self.comments[ln])
                ln -= 1
            text = " ".join(parts)
            for m in _ANNOT_RE.finditer(text):
                if m.group(1) == "holds":
                    info.holds.add(m.group(2).strip())
                elif m.group(1) == "single-owner":
                    info.single_owner = m.group(2).strip() or "unspecified"
                elif m.group(1) == "boundary":
                    info.boundary = m.group(2).strip()
                elif m.group(3) == "host-only":
                    info.host_only = True
                elif m.group(3) == "event-forwarder":
                    info.event_forwarder = True
            out[node] = info
        return out

    # -- guarded attributes --------------------------------------------

    def _collect_guards(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            # same-line comment wins over the line above (adjacent declarations
            # each carry their own trailing annotation)
            m = _GUARDED_RE.search(self.comments.get(node.lineno, ""))
            if not m:
                m = _GUARDED_RE.search(self.comments.get(node.lineno - 1, ""))
            if not m:
                continue
            lock = m.group(1)
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                    if self.enclosing_class(node) is not None:
                        self.guarded_attrs[tgt.attr] = lock
                        self.guard_decl_lines.add(node.lineno)
                elif isinstance(tgt, ast.Name):
                    cls = self.enclosing_class(node)
                    fn = self.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    if cls is None and fn is None:  # module-level global
                        self.guarded_globals[tgt.id] = lock
                        self.guard_decl_lines.add(node.lineno)

    # -- with-block lock spans -----------------------------------------

    def with_lock_spans(self) -> List[Tuple[str, int, int]]:
        """``(lock_name, first_line, last_line)`` for every ``with`` item that
        looks like a lock acquisition (``with self._lock:`` / ``with LOCK:``)."""
        spans: List[Tuple[str, int, int]] = []
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                expr = item.context_expr
                name: Optional[str] = None
                if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) and expr.value.id in ("self", "cls"):
                    name = expr.attr
                elif isinstance(expr, ast.Name):
                    name = expr.id
                if name is not None:
                    spans.append((name, node.lineno, node.end_lineno or node.lineno))
        return spans


def _is_under(path: Path, root: Path) -> bool:
    try:
        path.resolve().relative_to(root.resolve())
        return True
    except ValueError:
        return False


class Project:
    """The analysis context: root dir, file set, lazily extracted registries."""

    def __init__(self, root: Path, paths: Sequence[Path]) -> None:
        self.root = Path(root).resolve()
        self.files: List[Path] = []
        pkg = (self.root / "torchmetrics_tpu").resolve()
        #: whether the analyzed set covers the whole package — whole-tree
        #: checks (e.g. the TM504 orphan scan) only make sense then
        self.full_package = False
        for p in paths:
            p = Path(p)
            if p.is_dir():
                self.files.extend(sorted(q for q in p.rglob("*.py") if "__pycache__" not in q.parts))
                res = p.resolve()
                if res == pkg or _is_under(pkg, res):
                    self.full_package = True
            elif p.suffix == ".py":
                self.files.append(p)
        self._registry_cache: Dict[str, Any] = {}
        #: literal event kinds observed at record() sites (filled by the
        #: events rule during the file pass; read by its project pass)
        self.recorded_kinds: Set[str] = set()

    def package_file(self, rel: str) -> Optional[Path]:
        p = self.root / rel
        return p if p.is_file() else None

    def module_name(self, path: Path) -> str:
        """Dotted module path of a file relative to the project root."""
        try:
            rel = path.resolve().relative_to(self.root)
        except ValueError:
            return path.stem
        parts = list(rel.parts)
        parts[-1] = parts[-1][:-3]  # drop .py
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def registry(self, key: str, loader) -> Any:
        if key not in self._registry_cache:
            self._registry_cache[key] = loader(self)
        return self._registry_cache[key]


# ------------------------------------------------------------------ baseline


def finding_fingerprints(findings: Iterable[Finding], lines_by_path: Dict[str, List[str]]) -> List[Finding]:
    """Attach content-addressed fingerprints (stable across line drift)."""
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[Finding] = []
    for f in findings:
        lines = lines_by_path.get(f.path, [])
        content = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        key = (f.rule, f.path, content)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        fp = f"{f.rule}|{f.path}|{content}|{idx}"
        out.append(Finding(f.rule, f.path, f.line, f.message, fingerprint=fp))
    return out


def load_baseline(path: Path) -> Set[str]:
    if not path.is_file():
        return set()
    data = json.loads(path.read_text())
    return {entry["fingerprint"] for entry in data.get("findings", [])}


def save_baseline(path: Path, findings: Sequence[Finding]) -> None:
    data = {
        "version": 1,
        "findings": [
            {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint}
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    path.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")


# ------------------------------------------------------------------ runner


def run_lint(
    paths: Sequence[Path],
    root: Optional[Path] = None,
    rules: Optional[Set[str]] = None,
    baseline_path: Optional[Path] = None,
) -> Dict[str, Any]:
    """Run every rule family; returns findings, baselined + stale splits."""
    from tools.tmlint import (
        rules_counters,
        rules_events,
        rules_knobs,
        rules_locks,
        rules_persist,
        rules_riders,
        rules_slo,
        rules_transfer,
    )

    root = Path(root).resolve() if root is not None else Path.cwd()
    project = Project(root, paths)
    families = (
        rules_transfer, rules_knobs, rules_riders, rules_counters, rules_events,
        rules_locks, rules_persist, rules_slo,
    )

    findings: List[Finding] = []
    lines_by_path: Dict[str, List[str]] = {}
    for path in project.files:
        try:
            sf = SourceFile(path, root)
        except SyntaxError as err:
            findings.append(Finding("TM000", str(path), err.lineno or 1, f"syntax error: {err.msg}"))
            continue
        lines_by_path[sf.relpath] = sf.lines
        for fam in families:
            check = getattr(fam, "check_file", None)
            if check is not None:
                findings.extend(check(project, sf))
    for fam in families:
        check = getattr(fam, "check_project", None)
        if check is not None:
            for f in check(project):
                findings.append(f)
                if f.path not in lines_by_path:
                    p = root / f.path
                    lines_by_path[f.path] = p.read_text().splitlines() if p.is_file() else []

    if rules:
        findings = [f for f in findings if f.rule in rules]
    findings = finding_fingerprints(findings, lines_by_path)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    known = load_baseline(baseline_path) if baseline_path else set()
    new = [f for f in findings if f.fingerprint not in known]
    baselined = [f for f in findings if f.fingerprint in known]
    stale = sorted(known - {f.fingerprint for f in findings})
    return {"findings": findings, "new": new, "baselined": baselined, "stale": stale}
