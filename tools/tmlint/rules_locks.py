"""TM6xx — lock discipline for the cross-thread tiers (scan/async/serve).

The PR-13 review passes fixed, by hand, a class of race where shared
swap/FIFO/writeback state was touched off-lock. This rule family makes the
locking contract *declared and checked*:

- an attribute's declaring assignment carries ``# guarded-by: <lock>``;
- **TM601 unguarded-access** — any other read/write of that attribute that is
  not (a) lexically inside a ``with <lock>``/``with self.<lock>`` block,
  (b) inside a function annotated ``# tmlint: holds(<lock>)`` (the
  ``*_locked`` convention: every caller holds the lock), or (c) inside a
  function annotated ``# tmlint: single-owner(<role>)`` (provably one
  thread). Benign racy peeks must be explicit: ``# tmlint: disable=TM601``
  with a justification.
- **TM602 undeclared-lock** — a ``threading.Lock/RLock/Condition`` created in
  a cross-thread module with no ``guarded-by`` declaration naming it: a lock
  that protects nothing *declared* protects nothing *checked*.
- **TM603 unknown-lock** — a ``guarded-by``/``holds`` annotation naming a
  lock that is never created in the file (typo catcher).

Scope: ``engine/scan.py``, ``engine/async_dispatch.py``, ``serve/*`` (the
modules where a worker/scrape thread runs against the hot loop), plus any
file carrying ``# tmlint: scope=locks`` (test fixtures).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.tmlint.core import Finding, Project, SourceFile

_SCOPE_SUFFIXES = ("engine/scan.py", "engine/async_dispatch.py", "engine/persist.py")
_SCOPE_DIRS = ("/serve/",)
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def _in_scope(sf: SourceFile) -> bool:
    if "locks" in sf.scopes:
        return True
    rel = "/" + sf.relpath
    return rel.endswith(_SCOPE_SUFFIXES) or any(d in rel for d in _SCOPE_DIRS)


def _lock_assignments(sf: SourceFile) -> Dict[str, int]:
    """Lock-object names created in this file -> first creation line."""
    out: Dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        fn = node.value.func
        ctor = fn.attr if isinstance(fn, ast.Attribute) else (fn.id if isinstance(fn, ast.Name) else None)
        if ctor not in _LOCK_CTORS:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                out.setdefault(tgt.attr, node.lineno)
            elif isinstance(tgt, ast.Name):
                out.setdefault(tgt.id, node.lineno)
    return out


def check_file(project: Project, sf: SourceFile) -> List[Finding]:
    if not _in_scope(sf):
        return []
    findings: List[Finding] = []
    locks = _lock_assignments(sf)
    guarded_locks = set(sf.guarded_attrs.values()) | set(sf.guarded_globals.values())
    spans = sf.with_lock_spans()

    # TM602: every created lock must guard something declared
    for name, lineno in sorted(locks.items()):
        if name not in guarded_locks and not sf.suppressed("TM602", lineno):
            findings.append(
                Finding(
                    "TM602", sf.relpath, lineno,
                    f"lock {name!r} is created here but no attribute declares"
                    " '# guarded-by: {0}' — declare the state it protects so the"
                    " discipline is checkable".format(name),
                )
            )
    # TM603: every referenced lock must exist
    for attr, lock in sorted({**sf.guarded_attrs, **sf.guarded_globals}.items()):
        if lock not in locks and not sf.suppressed("TM603", 1):
            findings.append(
                Finding(
                    "TM603", sf.relpath, 1,
                    f"attribute {attr!r} declares guarded-by: {lock} but no such lock"
                    " is created in this file",
                )
            )
    for info in sf.functions.values():
        for lock in sorted(info.holds):
            if lock not in locks:
                findings.append(
                    Finding(
                        "TM603", sf.relpath, info.node.lineno,
                        f"holds({lock}) names a lock never created in this file",
                    )
                )

    def inside(lock: str, lineno: int) -> bool:
        return any(name == lock and a <= lineno <= b for name, a, b in spans)

    # TM601: instance-attribute + module-global accesses. single-owner
    # exemptions are collected per attribute so that the SAME attribute
    # exempted under two DIFFERENT roles (caller vs worker = two threads)
    # still fails — that is precisely the cross-thread race class.
    owner_roles: Dict[str, Dict[str, int]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) and node.value.id == "self":
            lock = sf.guarded_attrs.get(node.attr)
            if lock is not None and sf.enclosing_class(node) is not None:
                findings.extend(_check_access(sf, node, node.attr, lock, inside, owner_roles))
        elif isinstance(node, ast.Name) and node.id in sf.guarded_globals:
            findings.extend(
                _check_access(sf, node, node.id, sf.guarded_globals[node.id], inside, owner_roles)
            )
    for attr, roles in sorted(owner_roles.items()):
        if len(roles) > 1:
            findings.append(
                Finding(
                    "TM601", sf.relpath, min(roles.values()),
                    f"attribute {attr!r} is accessed off-lock in single-owner functions"
                    f" of DIFFERENT roles ({', '.join(sorted(roles))}) — two owners are"
                    " two threads; take the lock in one of them",
                )
            )
    return findings


def _check_access(
    sf: SourceFile, node: ast.AST, attr: str, lock: str, inside, owner_roles: Dict[str, Dict[str, int]]
) -> List[Finding]:
    lineno = node.lineno
    if lineno in sf.guard_decl_lines:
        return []
    if inside(lock, lineno):
        return []
    info = sf.enclosing_function(node)
    if info is not None and lock in info.holds:
        return []
    if info is not None and info.single_owner is not None:
        owner_roles.setdefault(attr, {}).setdefault(info.single_owner, lineno)
        return []
    if sf.suppressed("TM601", lineno):
        return []
    return [
        Finding(
            "TM601", sf.relpath, lineno,
            f"access to {attr!r} (guarded-by: {lock}) outside a 'with {lock}' block —"
            f" take the lock, annotate the function (# tmlint: holds({lock}) /"
            " single-owner(<role>)), or justify a benign peek with a disable comment",
        )
    ]
