"""TM7xx — durability discipline for the persist tier.

Everything the serving story trusts across a process boundary — elastic
snapshots (``parallel/elastic.py``) and the persistent executable cache +
prewarm manifest (``engine/persist.py``) — rides ONE write contract: a
durable file either exists complete or not at all. The PR-5 snapshot code
established it by convention (``.tmp`` + flush + fsync + ``os.replace``);
this family makes it checked:

- **TM701 non-atomic durable write** — a function in the persist tier that
  opens a file for (over)writing must, in the same function, both fsync the
  handle (``os.fsync``) and land it with an atomic ``os.replace`` — a bare
  ``open(final, "wb")`` leaves a torn-artifact crash window a reader can
  observe.
- **TM702 unflushed durable append** — an append-mode open (the manifest
  journal) must flush AND fsync in the same function: an append that dies in
  the page cache silently loses the signature rows a later prewarm replays.

Scope: ``engine/persist.py``, ``parallel/elastic.py``, plus any file carrying
``# tmlint: scope=persist`` (test fixtures). Read-mode opens are exempt;
``# tmlint: disable=TM701/TM702`` with a justification marks a deliberate
non-durable write (none exist in-tree today).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.tmlint.core import Finding, Project, SourceFile

_SCOPE_SUFFIXES = ("engine/persist.py", "parallel/elastic.py")


def _in_scope(sf: SourceFile) -> bool:
    if "persist" in sf.scopes:
        return True
    return ("/" + sf.relpath).endswith(_SCOPE_SUFFIXES)


def _open_mode(node: ast.Call) -> Optional[str]:
    """The literal mode of an ``open(...)`` call; None for non-open/dynamic."""
    fn = node.func
    if not (isinstance(fn, ast.Name) and fn.id == "open"):
        return None
    if len(node.args) >= 2:
        mode = node.args[1]
    else:
        mode = next((kw.value for kw in node.keywords if kw.arg == "mode"), None)
    if mode is None:
        return "r"  # open(path) defaults to text read
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None  # dynamic mode: out of the rule's reach


def _calls_attr(body: ast.AST, owner: str, attr: str) -> bool:
    for node in ast.walk(body):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == attr
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == owner
        ):
            return True
    return False


def _calls_method(body: ast.AST, attr: str) -> bool:
    return any(
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == attr
        for node in ast.walk(body)
    )


def check_file(project: Project, sf: SourceFile) -> List[Finding]:
    if not _in_scope(sf):
        return []
    findings: List[Finding] = []
    for fn_node, info in sf.functions.items():
        has_replace = _calls_attr(fn_node, "os", "replace")
        has_fsync = _calls_attr(fn_node, "os", "fsync")
        has_flush = _calls_method(fn_node, "flush")
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            # opens inside nested defs are that function's own finding
            if sf.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef)) is not fn_node:
                continue
            mode = _open_mode(node)
            if mode is None or ("w" not in mode and "a" not in mode and "+" not in mode):
                continue
            if "a" in mode:
                if (not has_flush or not has_fsync) and not sf.suppressed("TM702", node.lineno):
                    findings.append(
                        Finding(
                            "TM702", sf.relpath, node.lineno,
                            f"append-mode durable write in {info.qualname!r} without"
                            " flush+os.fsync in the same function — a journal line"
                            " dying in the page cache silently loses prewarm rows",
                        )
                    )
            else:
                if (not has_replace or not has_fsync) and not sf.suppressed("TM701", node.lineno):
                    findings.append(
                        Finding(
                            "TM701", sf.relpath, node.lineno,
                            f"durable write in {info.qualname!r} without the atomic"
                            " contract (os.fsync + os.replace in the same function) —"
                            " write to a .tmp sibling, fsync, then os.replace it in",
                        )
                    )
    return findings
