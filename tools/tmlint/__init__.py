"""tmlint — the AST-based invariant analyzer for torchmetrics_tpu.

Thirteen PRs accreted cross-cutting invariants that runtime guards and CI
greps enforced piecemeal; tmlint checks them from the source text, before a
TPU — or even a test run — is needed. Rule families (catalog with IDs in
``docs/pages/static-analysis.md``):

=======  ==============================================================
TM1xx    transfer purity — host readbacks only at registered boundaries
TM2xx    env-knob contract — fail-loud parsers + doc lockstep
TM301    rider-key lockstep — one spelling site for reserved pytree keys
TM4xx    counter lockstep — EngineStats ↔ telemetry ↔ unit conventions
TM5xx    event taxonomy — declared, documented, recorded
TM6xx    lock discipline — guarded-by annotations on cross-thread state
TM8xx    SLO registry — documented ids bound to real signals
=======  ==============================================================

Run ``python -m tools.tmlint torchmetrics_tpu/`` from the repo root (see
``scripts/ci.sh``), or ``--json`` for machine-readable finding counts.
"""

from tools.tmlint.core import Finding, Project, SourceFile, run_lint

#: rule catalog: id -> one-line description (the docs page mirrors this)
RULES = {
    "TM101": "unsanctioned host readback in engine/parallel/serve",
    "TM102": "float()/int() over a jnp-derived value (implicit readback)",
    "TM103": "transfer_allowed label / boundary() not registered",
    "TM201": "TORCHMETRICS_TPU_* env read outside its registered parser",
    "TM202": "dynamic environ read outside the registered generic parsers",
    "TM203": "registered env knob undocumented in docs/api/root.md",
    "TM204": "documented env knob missing from KNOB_REGISTRY",
    "TM301": "reserved rider-key literal outside engine/statespec.py",
    "TM401": "EngineStats counter missing from the telemetry export table",
    "TM402": "telemetry export row for a nonexistent counter",
    "TM403": "exported series name violates the unit-suffix convention",
    "TM404": "EngineStats.__init__/reset no longer iterate _COUNTER_FIELDS",
    "TM501": "record() kind not declared in EVENT_KINDS",
    "TM502": "dynamic event kind outside an event-forwarder",
    "TM503": "declared event kind undocumented in observability.md",
    "TM504": "declared event kind never recorded (dead taxonomy)",
    "TM601": "guarded-by attribute accessed outside its lock",
    "TM602": "lock created with no guarded-by declarations",
    "TM603": "guarded-by/holds names a lock that does not exist",
    "TM801": "registered SLO id undocumented in observability.md",
    "TM802": "documented slo:<id> token missing from SLO_REGISTRY",
    "TM803": "SLO spec bound to a nonexistent signal or denominator",
}

__all__ = ["Finding", "Project", "RULES", "SourceFile", "run_lint"]
