"""TM301 — rider-key lockstep: the reserved pytree keys have ONE spelling site.

``__sentinel__`` / ``__quarantine__`` / ``__compensation__`` are structural:
the bucketing pad-subtract, the transactional rollback, the packed-sync
layout, and the scan carry all special-case them. A re-spelled literal in a
new consumer silently drifts out of that contract the day the canonical set
changes. Rule: the literals may appear only in ``engine/statespec.py`` (the
canonical ``RIDER_KEYS`` declaration) — everywhere else import
``RIDER_KEYS`` / ``PAD_EXEMPT_KEYS`` / the ``*_KEY`` aliases. Docstrings are
exempt (prose, not pytree keys).
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.tmlint.core import Finding, Project, SourceFile
from tools.tmlint.registries import rider_keys

_CANONICAL_SUFFIX = "engine/statespec.py"


def _docstring_nodes(tree: ast.AST) -> Set[ast.AST]:
    """The Constant nodes that are module/class/function docstrings."""
    out: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and isinstance(body[0].value, ast.Constant):
                out.add(body[0].value)
    return out


def check_file(project: Project, sf: SourceFile) -> List[Finding]:
    if ("/" + sf.relpath).endswith("/" + _CANONICAL_SUFFIX):
        return []
    keys = rider_keys(project)
    docstrings = _docstring_nodes(sf.tree)
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Constant) or not isinstance(node.value, str):
            continue
        if node.value not in keys or node in docstrings:
            continue
        if sf.suppressed("TM301", node.lineno):
            continue
        findings.append(
            Finding(
                "TM301", sf.relpath, node.lineno,
                f"reserved rider key {node.value!r} spelled as a literal outside"
                " engine/statespec.py — import RIDER_KEYS/PAD_EXEMPT_KEYS (or the"
                " *_KEY aliases) instead",
            )
        )
    return findings
