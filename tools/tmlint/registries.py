"""tmlint registry extraction — read the package's declared invariants from
source text (never by importing the package).

Each accessor parses the module that CANONICALLY declares a registry:

===========================  =================================================
``EVENT_KINDS``              ``torchmetrics_tpu/diag/trace.py``
``TRANSFER_LABELS`` (+ prefixes)  ``torchmetrics_tpu/diag/transfer_guard.py``
``KNOB_REGISTRY`` (+ generic parsers)  ``torchmetrics_tpu/engine/config.py``
``RIDER_KEYS``               ``torchmetrics_tpu/engine/statespec.py``
``_COUNTER_FIELDS``          ``torchmetrics_tpu/engine/stats.py``
counter/histogram export tables + unit rule  ``torchmetrics_tpu/diag/telemetry.py``
``SLO_REGISTRY``             ``torchmetrics_tpu/diag/slo.py``
===========================  =================================================

The mini-evaluator below resolves module-level assignments whose value is a
constant expression over literals, earlier module constants, and the builtin
container constructors (``frozenset``/``set``/``tuple``/``list``/``dict``) —
enough for every registry above without executing package code.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Dict, Optional

from tools.tmlint.core import Project

_CONSTRUCTORS = {"frozenset": frozenset, "set": set, "tuple": tuple, "list": list, "dict": dict}


def _resolve(node: ast.AST, env: Dict[str, Any]) -> Any:
    """Evaluate a constant expression over literals + known module constants."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise ValueError(f"unresolvable name {node.id!r}")
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = [_resolve(e, env) for e in node.elts]
        return tuple(vals) if isinstance(node, ast.Tuple) else vals
    if isinstance(node, ast.Set):
        return {_resolve(e, env) for e in node.elts}
    if isinstance(node, ast.Dict):
        return {
            _resolve(k, env): _resolve(v, env)
            for k, v in zip(node.keys, node.values)
            if k is not None
        }
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id in _CONSTRUCTORS:
        ctor = _CONSTRUCTORS[node.func.id]
        if not node.args:
            return ctor()
        return ctor(_resolve(node.args[0], env))
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _resolve(node.left, env) + _resolve(node.right, env)
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                parts.append(str(_resolve(v.value, env)))
            else:
                raise ValueError("unresolvable f-string part")
        return "".join(parts)
    raise ValueError(f"unresolvable node {type(node).__name__}")


def module_constants(path: Path) -> Dict[str, Any]:
    """Every module-level NAME whose assigned value resolves constantly."""
    tree = ast.parse(path.read_text())
    env: Dict[str, Any] = {}
    for node in tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                try:
                    env[tgt.id] = _resolve(value, env)
                except ValueError:
                    pass
    return env


def _constants_of(project: Project, rel: str) -> Dict[str, Any]:
    path = project.package_file(rel)
    return module_constants(path) if path is not None else {}


def event_kinds(project: Project) -> frozenset:
    def load(p: Project):
        return frozenset(_constants_of(p, "torchmetrics_tpu/diag/trace.py").get("EVENT_KINDS", ()))

    return project.registry("event_kinds", load)


def transfer_labels(project: Project):
    def load(p: Project):
        env = _constants_of(p, "torchmetrics_tpu/diag/transfer_guard.py")
        return (
            frozenset(env.get("TRANSFER_LABELS", ())),
            tuple(env.get("TRANSFER_LABEL_PREFIXES", ())),
        )

    return project.registry("transfer_labels", load)


def knob_registry(project: Project):
    def load(p: Project):
        env = _constants_of(p, "torchmetrics_tpu/engine/config.py")
        return (
            dict(env.get("KNOB_REGISTRY", {})),
            tuple(env.get("GENERIC_KNOB_PARSERS", ())),
        )

    return project.registry("knob_registry", load)


def rider_keys(project: Project) -> frozenset:
    def load(p: Project):
        env = _constants_of(p, "torchmetrics_tpu/engine/statespec.py")
        keys = env.get("RIDER_KEYS")
        if keys:
            return frozenset(keys)
        # self-hosting fallback: the reserved keys are part of the rule's
        # contract even if the registry module is missing from the target tree
        return frozenset({"__sentinel__", "__quarantine__", "__compensation__"})

    return project.registry("rider_keys", load)


def counter_fields(project: Project) -> tuple:
    def load(p: Project):
        return tuple(_constants_of(p, "torchmetrics_tpu/engine/stats.py").get("_COUNTER_FIELDS", ()))

    return project.registry("counter_fields", load)


def telemetry_tables(project: Project) -> Dict[str, Any]:
    def load(p: Project):
        env = _constants_of(p, "torchmetrics_tpu/diag/telemetry.py")
        return {
            "prefix": env.get("_PREFIX", "tm_tpu"),
            "counter_help": dict(env.get("_COUNTER_HELP", {})),
            "export_name": dict(env.get("_COUNTER_EXPORT_NAME", {})),
            "export_scale": dict(env.get("_COUNTER_EXPORT_SCALE", {})),
            "hist_series": dict(env.get("_HIST_SERIES", {})),
            "unit_suffixes": tuple(env.get("UNIT_SUFFIXES", ())),
            "unitless": frozenset(env.get("UNITLESS_COUNT_FAMILIES", ())),
        }

    return project.registry("telemetry_tables", load)


def slo_registry(project: Project) -> Dict[str, Any]:
    def load(p: Project):
        return dict(_constants_of(p, "torchmetrics_tpu/diag/slo.py").get("SLO_REGISTRY", {}))

    return project.registry("slo_registry", load)


def docs_text(project: Project, rel: str) -> Optional[str]:
    key = f"docs::{rel}"

    def load(p: Project):
        path = p.root / rel
        return path.read_text() if path.is_file() else None

    return project.registry(key, load)
