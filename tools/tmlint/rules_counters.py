"""TM4xx — counter lockstep: every ``EngineStats`` field is reset, exported,
and exposition-conformant — checked from the source text.

``tests/test_telemetry.py`` proves this at runtime for the counters a test
run happens to touch; these rules prove it for EVERY field, before any run:

- **TM401 counter-not-exported** — a ``_COUNTER_FIELDS`` entry with no
  ``_COUNTER_HELP`` row in ``diag/telemetry.py`` (it would silently vanish
  from ``export_prometheus``).
- **TM402 counter-table-orphan** — a ``_COUNTER_HELP`` /
  ``_COUNTER_EXPORT_NAME`` / ``_COUNTER_EXPORT_SCALE`` key that is not a
  ``_COUNTER_FIELDS`` entry (a stale export row for a removed counter).
- **TM403 series-unit-violation** — an exported family name (counter,
  histogram, or explicitly emitted literal) that neither carries a unit
  suffix (``UNIT_SUFFIXES``) nor sits in the pure-count allowlist
  (``UNITLESS_COUNT_FAMILIES``).
- **TM404 counter-reset-drift** — ``EngineStats.__init__`` / ``reset`` no
  longer iterate ``_COUNTER_FIELDS`` (a hand-maintained field list is exactly
  the lockstep this registry exists to prevent).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, List, Optional, Set

from tools.tmlint.core import Finding, Project
from tools.tmlint.registries import counter_fields, module_constants, telemetry_tables

_STATS_REL = "torchmetrics_tpu/engine/stats.py"
_TELEMETRY_REL = "torchmetrics_tpu/diag/telemetry.py"
_FAMILY_STRIP = ("_bucket", "_sum", "_count")


def _base_family(name: str) -> str:
    for suffix in _FAMILY_STRIP:
        if name.endswith(suffix):
            name = name[: -len(suffix)]
            break
    if name.endswith("_total"):
        name = name[: -len("_total")]
    return name


def _unit_ok(family: str, tables: Dict[str, Any]) -> bool:
    base = _base_family(family)
    return base.endswith(tuple(tables["unit_suffixes"])) or base in tables["unitless"]


def _literal_families(project: Project) -> Dict[str, int]:
    """Family names emitted as literals/f-strings in export_prometheus."""
    path = project.package_file(_TELEMETRY_REL)
    if path is None:
        return {}
    consts = module_constants(path)
    tree = ast.parse(path.read_text())
    out: Dict[str, int] = {}
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    for node in ast.walk(tree):
        value: Optional[str] = None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            value = node.value
        elif isinstance(node, ast.JoinedStr):
            parts = []
            ok = True
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif isinstance(v, ast.FormattedValue) and isinstance(v.value, ast.Name):
                    ref = consts.get(v.value.id)
                    if isinstance(ref, str):
                        parts.append(ref)
                    else:
                        ok = False
                        break
                else:
                    ok = False
                    break
            if ok:
                value = "".join(parts)
        if value and value.startswith(consts.get("_PREFIX", "tm_tpu") + "_") and name_re.match(value):
            out.setdefault(value, node.lineno)
    return out


def check_project(project: Project) -> List[Finding]:
    fields = counter_fields(project)
    tables = telemetry_tables(project)
    if not fields or not tables["counter_help"]:
        return []
    findings: List[Finding] = []
    field_set: Set[str] = set(fields)
    help_set = set(tables["counter_help"])
    prefix = tables["prefix"]

    for f in sorted(field_set - help_set):
        findings.append(
            Finding(
                "TM401", _STATS_REL, 1,
                f"EngineStats counter {f!r} has no _COUNTER_HELP row in"
                " diag/telemetry.py — it will not export to Prometheus",
            )
        )
    for table_name in ("counter_help", "export_name", "export_scale"):
        for f in sorted(set(tables[table_name]) - field_set):
            findings.append(
                Finding(
                    "TM402", _TELEMETRY_REL, 1,
                    f"telemetry table {table_name} entry {f!r} is not an"
                    " EngineStats _COUNTER_FIELDS member (stale export row)",
                )
            )

    # unit conformance: counters (after export-name/scale mapping) ...
    for f in sorted(field_set & help_set):
        scaled = tables["export_scale"].get(f)
        name = scaled[0] if scaled else tables["export_name"].get(f, f)
        family = f"{prefix}_{name}_total"
        if not _unit_ok(family, tables):
            findings.append(
                Finding(
                    "TM403", _TELEMETRY_REL, 1,
                    f"counter family {family!r} lacks a unit suffix"
                    f" ({tables['unit_suffixes']}) and is not allowlisted in"
                    " UNITLESS_COUNT_FAMILIES",
                )
            )
    # ... histogram families ...
    for series, spec in sorted(tables["hist_series"].items()):
        family = f"{prefix}_{spec[0]}"
        if not _unit_ok(family, tables):
            findings.append(
                Finding(
                    "TM403", _TELEMETRY_REL, 1,
                    f"histogram family {family!r} (series {series!r}) lacks a unit"
                    " suffix and is not allowlisted in UNITLESS_COUNT_FAMILIES",
                )
            )
    # ... and explicitly emitted literal families (serve/ledger/event rows)
    for family, lineno in sorted(_literal_families(project).items()):
        if not _unit_ok(family, tables):
            findings.append(
                Finding(
                    "TM403", _TELEMETRY_REL, lineno,
                    f"emitted family {family!r} lacks a unit suffix and is not"
                    " allowlisted in UNITLESS_COUNT_FAMILIES",
                )
            )

    findings.extend(_check_reset_lockstep(project))
    return findings


def _check_reset_lockstep(project: Project) -> List[Finding]:
    path = project.package_file(_STATS_REL)
    if path is None:
        return []
    tree = ast.parse(path.read_text())
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "EngineStats":
            for required in ("__init__", "reset"):
                fn = next(
                    (n for n in node.body if isinstance(n, ast.FunctionDef) and n.name == required),
                    None,
                )
                if fn is None or not _iterates_fields(fn):
                    findings.append(
                        Finding(
                            "TM404", _STATS_REL, (fn or node).lineno,
                            f"EngineStats.{required} must iterate _COUNTER_FIELDS"
                            " (setattr loop) so new counters reset/initialize in"
                            " lockstep with the registry",
                        )
                    )
    return findings


def _iterates_fields(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.For) and isinstance(node.iter, ast.Name) and node.iter.id == "_COUNTER_FIELDS":
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) and isinstance(inner.func, ast.Name) and inner.func.id == "setattr":
                    return True
    return False
