"""TM8xx — the SLO-registry contract: every declared objective is documented
and bound to a signal that actually exists.

``SLO_REGISTRY`` (``diag/slo.py``) follows the KNOB_REGISTRY three-touch
convention: an objective is *declared* in the registry, *bound* to a real
histogram series or counter field, and *documented* as a backticked
``slo:<id>`` token in ``docs/pages/observability.md``. Drift in any direction
makes the readiness surface lie:

- **TM801 slo-undocumented** — a registered SLO id with no ``slo:<id>`` token
  in the observability page. An operator paged by a 503 naming that SLO has
  no prose to read.
- **TM802 slo-unimplemented** — a ``slo:<id>`` doc token with no registry
  entry (documented but gone — or renamed without updating the page).
- **TM803 slo-ghost-signal** — a spec bound to a signal that does not exist:
  a ``quantile`` spec whose ``signal`` is not a ``_HIST_SERIES`` key, a
  ``rate``/``ratio`` spec whose ``signal`` (or ``denominator``) is not an
  ``EngineStats`` counter field. An SLO over a ghost signal measures nothing
  and silently never breaches.
"""

from __future__ import annotations

import re
from typing import List

from tools.tmlint.core import Finding, Project
from tools.tmlint.registries import counter_fields, docs_text, slo_registry, telemetry_tables

_DOCS_REL = "docs/pages/observability.md"
_SLO_REL = "torchmetrics_tpu/diag/slo.py"

#: the documentation token convention: a backticked ``slo:<id>``
_TOKEN_RE = re.compile(r"`slo:([a-z0-9-]+)`")


def check_project(project: Project) -> List[Finding]:
    registry = slo_registry(project)
    if not registry:
        return []
    findings: List[Finding] = []

    text = docs_text(project, _DOCS_REL)
    if text is not None:
        documented = set(_TOKEN_RE.findall(text))
        for slo_id in sorted(set(registry) - documented):
            findings.append(
                Finding(
                    "TM801", _SLO_REL, 1,
                    f"SLO {slo_id!r} is registered but undocumented — add a"
                    f" `slo:{slo_id}` token (with prose) to {_DOCS_REL}",
                )
            )
        for slo_id in sorted(documented - set(registry)):
            findings.append(
                Finding(
                    "TM802", _DOCS_REL, 1,
                    f"doc token `slo:{slo_id}` has no SLO_REGISTRY entry —"
                    " register the objective in diag/slo.py or drop the stale doc",
                )
            )

    hist_series = set(telemetry_tables(project)["hist_series"])
    counters = set(counter_fields(project))
    for slo_id in sorted(registry):
        row = registry[slo_id]
        if not isinstance(row, dict):
            continue
        signal = row.get("signal")
        kind = row.get("kind")
        if kind == "quantile":
            if signal not in hist_series:
                findings.append(
                    Finding(
                        "TM803", _SLO_REL, 1,
                        f"SLO {slo_id!r} binds quantile signal {signal!r} which is"
                        " not a telemetry _HIST_SERIES key — it would never measure",
                    )
                )
        elif kind in ("rate", "ratio"):
            if signal not in counters:
                findings.append(
                    Finding(
                        "TM803", _SLO_REL, 1,
                        f"SLO {slo_id!r} binds {kind} signal {signal!r} which is"
                        " not an EngineStats counter field — it would never measure",
                    )
                )
            denom = row.get("denominator")
            if kind == "ratio" and denom not in counters:
                findings.append(
                    Finding(
                        "TM803", _SLO_REL, 1,
                        f"SLO {slo_id!r} has ratio denominator {denom!r} which is"
                        " not an EngineStats counter field — it would never measure",
                    )
                )
    return findings
