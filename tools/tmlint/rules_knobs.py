"""TM2xx — the env-knob contract: every ``TORCHMETRICS_TPU_*`` read routes
through its ONE registered fail-loud parser and stays in lockstep with the
knob documentation.

- **TM201 raw-env-read** — an ``os.environ.get`` / ``os.getenv`` /
  ``os.environ[...]`` read whose key resolves to a ``TORCHMETRICS_TPU_*``
  name, either (a) not registered in ``engine/config.py``'s
  ``KNOB_REGISTRY`` at all, or (b) read outside the registered parser
  function. The PR-7 env contract (unrecognized values fail loud) is only
  enforceable while every read goes through the one parser that implements it.
- **TM202 dynamic-env-read** — an environ read whose key is not statically
  resolvable, outside the registered generic parsers
  (``GENERIC_KNOB_PARSERS`` — the shared ``name``-parameter validators).
- **TM203 knob-undocumented** — a registered knob that never appears in
  ``docs/api/root.md`` (implemented but undocumented).
- **TM204 knob-unimplemented** — a ``TORCHMETRICS_TPU_*`` token in
  ``docs/api/root.md`` with no registry entry (documented but gone — or
  implemented without registration).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, List, Optional, Tuple

from tools.tmlint.core import Finding, Project, SourceFile
from tools.tmlint.registries import docs_text, knob_registry, module_constants

_KNOB_RE = re.compile(r"TORCHMETRICS_TPU_[A-Z0-9_]+")
_DOCS_REL = "docs/api/root.md"


_ENV_CALLS = ("os.environ.get", "os.getenv", "environ.get", "getenv")
_ENV_MAPPINGS = ("os.environ", "environ")


def _env_read_key(node: ast.AST) -> Optional[Tuple[ast.AST, ast.expr]]:
    """(site, key-expression) when ``node`` reads the process environment.

    Matches the aliased spellings too (``from os import environ, getenv``) —
    a knob read must not escape the contract by import style.
    """
    if isinstance(node, ast.Call):
        target = ast.unparse(node.func)
        if target in _ENV_CALLS and node.args:
            return node, node.args[0]
    if isinstance(node, ast.Subscript):
        if isinstance(node.value, (ast.Attribute, ast.Name)) and ast.unparse(node.value) in _ENV_MAPPINGS:
            return node, node.slice
    return None


def _resolve_key(expr: ast.expr, consts: Dict[str, Any]) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        val = consts.get(expr.id)
        return val if isinstance(val, str) else None
    return None


def check_file(project: Project, sf: SourceFile) -> List[Finding]:
    rel = sf.relpath
    in_package = rel.startswith("torchmetrics_tpu/")
    if not in_package and "knobs" not in sf.scopes:
        return []
    registry, generic = knob_registry(project)
    consts = module_constants(sf.path)
    module = project.module_name(sf.path) if in_package else sf.path.stem
    findings: List[Finding] = []

    for node in ast.walk(sf.tree):
        hit = _env_read_key(node)
        if hit is None:
            continue
        site, key_expr = hit
        info = sf.enclosing_function(site)
        qual = f"{module}:{info.qualname}" if info is not None else f"{module}:<module>"
        key = _resolve_key(key_expr, consts)
        if key is None:
            if qual in generic or sf.suppressed("TM202", site.lineno):
                continue
            findings.append(
                Finding(
                    "TM202", rel, site.lineno,
                    f"dynamic environment read in {qual} — only the registered generic"
                    f" parsers {list(generic)} may read a non-literal key",
                )
            )
            continue
        if not _KNOB_RE.fullmatch(key):
            continue  # not a package knob (LOCAL_RANK, debug vars, ...)
        if sf.suppressed("TM201", site.lineno):
            continue
        parser = registry.get(key)
        if parser is None:
            findings.append(
                Finding(
                    "TM201", rel, site.lineno,
                    f"env knob {key} is read here but not registered in"
                    " engine/config.py KNOB_REGISTRY — register its fail-loud parser"
                    " and document it in docs/api/root.md",
                )
            )
        elif qual != parser:
            findings.append(
                Finding(
                    "TM201", rel, site.lineno,
                    f"env knob {key} read outside its registered parser"
                    f" ({qual} != {parser}) — route the read through the parser so"
                    " the fail-loud contract stays single-sourced",
                )
            )
    return findings


def check_project(project: Project) -> List[Finding]:
    registry, _ = knob_registry(project)
    if not registry:
        return []
    text = docs_text(project, _DOCS_REL)
    if text is None:
        return []
    documented = set(_KNOB_RE.findall(text))
    config_rel = "torchmetrics_tpu/engine/config.py"
    findings: List[Finding] = []
    for knob in sorted(set(registry) - documented):
        findings.append(
            Finding(
                "TM203", config_rel, 1,
                f"env knob {knob} is registered (parser {registry[knob]}) but"
                f" undocumented — add it to {_DOCS_REL}",
            )
        )
    for knob in sorted(documented - set(registry)):
        findings.append(
            Finding(
                "TM204", _DOCS_REL, 1,
                f"env knob {knob} is documented but has no KNOB_REGISTRY entry —"
                " register its parser in engine/config.py or drop the stale doc",
            )
        )
    return findings
