"""TM1xx — transfer purity: host readbacks only at registered boundaries.

The north-star invariant ("zero host transfers in the hot loop",
``BASELINE.json``) is a *static* property of the source: a readback call that
is not lexically inside a sanctioned ``transfer_allowed(...)`` scope will
eventually execute outside one. Rules:

- **TM101 unsanctioned-host-readback** — a call to ``np.asarray`` /
  ``np.array`` / ``jax.device_get`` / ``.item()`` / ``.tolist()`` inside the
  hot-loop packages (``engine/``, ``parallel/``, ``serve/``) that is not
  enclosed in a ``with transfer_allowed(...)`` block, not inside a function
  annotated ``# tmlint: boundary(<label>)`` (asserting it only runs inside
  that registered boundary) or ``# tmlint: host-only`` (asserting no device
  buffer reaches it), and not suppressed.
- **TM102 device-scalar-coercion** — ``float(x)`` / ``int(x)`` where ``x`` is
  a ``jnp.*`` call result (directly or through a same-function local): the
  implicit ``__float__``/``__int__`` is a device→host readback.
- **TM103 unregistered-transfer-label** — a ``transfer_allowed("<label>")``
  call or ``boundary(<label>)`` annotation whose label is not declared in
  ``diag/transfer_guard.py``'s ``TRANSFER_LABELS`` (or covered by a
  registered prefix): sanctioned boundaries are a closed, reviewed set.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.tmlint.core import Finding, Project, SourceFile
from tools.tmlint.registries import transfer_labels

_READBACK_METHODS = {"item", "tolist"}
_NUMPY_NAMES = {"np", "numpy", "onp"}
_SCOPE_DIRS = ("/engine/", "/parallel/", "/serve/")
#: the guard machinery itself and its direct test double are out of scope
_EXEMPT_SUFFIXES = ("diag/transfer_guard.py",)


def _in_scope(sf: SourceFile) -> bool:
    if "transfer" in sf.scopes:
        return True
    rel = "/" + sf.relpath
    if rel.endswith(_EXEMPT_SUFFIXES):
        return False
    return any(d in rel for d in _SCOPE_DIRS)


def _is_transfer_allowed_call(node: ast.Call) -> bool:
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (fn.id if isinstance(fn, ast.Name) else None)
    return name == "transfer_allowed"


def _label_of(node: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(literal label, literal prefix) — prefix for ``"collective:" + x``."""
    if not node.args:
        return "", None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, None
    if (
        isinstance(arg, ast.BinOp)
        and isinstance(arg.op, ast.Add)
        and isinstance(arg.left, ast.Constant)
        and isinstance(arg.left.value, str)
    ):
        return None, arg.left.value
    return None, None


def _sanction_spans(sf: SourceFile) -> List[Tuple[int, int, ast.Call]]:
    spans = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call) and _is_transfer_allowed_call(expr):
                    spans.append((node.lineno, node.end_lineno or node.lineno, expr))
    return spans


def _readback_name(node: ast.Call) -> Optional[str]:
    """The flaggable readback this call is, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        base = fn.value
        base_name = base.id if isinstance(base, ast.Name) else None
        if fn.attr in ("asarray", "array") and base_name in _NUMPY_NAMES:
            return f"{base_name}.{fn.attr}"
        if fn.attr == "device_get" and base_name == "jax":
            return "jax.device_get"
        if fn.attr in _READBACK_METHODS and not node.args and not node.keywords:
            return f".{fn.attr}()"
    return None


def _jnp_locals(fn_node: ast.AST) -> Set[str]:
    """Names assigned from a ``jnp.*`` call anywhere in this function."""
    out: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and _is_jnp_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _is_jnp_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    while isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name) and fn.value.id == "jnp":
            return True
        fn = fn.value
    return False


def check_file(project: Project, sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    labels, prefixes = transfer_labels(project)

    def label_ok(label: Optional[str], prefix: Optional[str]) -> bool:
        if label is not None:
            return label in labels or any(label.startswith(p) for p in prefixes)
        if prefix is not None:
            return any(prefix.startswith(p) or p.startswith(prefix) for p in prefixes)
        return False

    # TM103 on every transfer_allowed site + boundary annotation (any file
    # inside the analyzed tree that uses the guard machinery)
    spans = _sanction_spans(sf)
    if not ("/" + sf.relpath).endswith(_EXEMPT_SUFFIXES):
        for _, _, call in spans:
            label, prefix = _label_of(call)
            if label == "":
                # a bare transfer_allowed() would sanction readbacks while
                # naming no reviewed boundary — exactly the drive-by the
                # registry exists to prevent
                if not sf.suppressed("TM103", call.lineno):
                    findings.append(
                        Finding(
                            "TM103", sf.relpath, call.lineno,
                            "transfer_allowed() without a label sanctions readbacks"
                            " anonymously — pass a label registered in"
                            " diag/transfer_guard.py TRANSFER_LABELS",
                        )
                    )
                continue
            if not label_ok(label, prefix) and not sf.suppressed("TM103", call.lineno):
                findings.append(
                    Finding(
                        "TM103", sf.relpath, call.lineno,
                        f"transfer_allowed label {label or prefix!r} is not registered in"
                        " diag/transfer_guard.py TRANSFER_LABELS",
                    )
                )
        for info in sf.functions.values():
            if info.boundary is not None and info.boundary not in labels:
                if not sf.suppressed("TM103", info.node.lineno):
                    findings.append(
                        Finding(
                            "TM103", sf.relpath, info.node.lineno,
                            f"boundary({info.boundary}) names a label not registered in"
                            " diag/transfer_guard.py TRANSFER_LABELS",
                        )
                    )

    if not _in_scope(sf):
        return findings

    def sanctioned(lineno: int) -> bool:
        return any(a <= lineno <= b for a, b, _ in spans)

    jnp_cache: Dict[ast.AST, Set[str]] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        info = sf.enclosing_function(node)
        exempt = info is not None and (info.boundary is not None or info.host_only)

        name = _readback_name(node)
        if name is not None:
            if sanctioned(node.lineno) or exempt or sf.suppressed("TM101", node.lineno):
                continue
            findings.append(
                Finding(
                    "TM101", sf.relpath, node.lineno,
                    f"host readback {name} outside any sanctioned transfer_allowed(...)"
                    " scope — wrap it in a registered boundary, annotate the enclosing"
                    " function (# tmlint: boundary(<label>) / host-only), or move the"
                    " read to the epoch boundary",
                )
            )
            continue

        # TM102: float()/int() over a jnp-derived value
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in ("float", "int") and len(node.args) == 1:
            arg = node.args[0]
            derived = _is_jnp_call(arg)
            if not derived and isinstance(arg, ast.Name):
                owner = sf.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                if owner is not None:
                    if owner not in jnp_cache:
                        jnp_cache[owner] = _jnp_locals(owner)
                    derived = arg.id in jnp_cache[owner]
            if derived and not sanctioned(node.lineno) and not exempt and not sf.suppressed("TM102", node.lineno):
                findings.append(
                    Finding(
                        "TM102", sf.relpath, node.lineno,
                        f"{fn.id}() over a jnp-derived value is an implicit device→host"
                        " readback — sanction it or keep the value on device",
                    )
                )
    return findings
